"""Versioned on-disk tuning store: JSON, atomic writes, replicated reads.

Schema (``SCHEMA_VERSION`` = 2)::

    {
      "schema_version": 2,
      "created": <wall-clock s of first write>,
      "entries": {
        "<device_kind>|<jax_version>|<model_signature>|<bucket>": {
          "config":  {<TrialConfig fields>},      # the winning config
          "objective": "train_scan_ms_per_step",  # what was minimized
          "value": 12.3,                          # winner's objective
          "default_value": 15.0,                  # default config's objective
          "trials_completed": 9,
          "trials_total": 12,
          "partial": false,     # true when the search died early; the
                                # entry is still the best of what finished
          "measured_at": <wall-clock s>,
          "search": {...}       # rung/budget bookkeeping, for forensics
        }
      }
    }

Key design points, each earned the hard way:

* **Atomic, incremental writes.** ``put`` + ``save`` rewrite the whole
  file via tmp+``os.replace`` after EVERY trial, so a killed or timed-out
  tuning run keeps everything measured so far (the rc=124 lesson from
  BENCH_r03/r04: a whole driver round died with finished work unrecorded).
* **Versioned and loudly incompatible.** A store whose ``schema_version``
  differs is rejected with :class:`StoreSchemaError`, never silently
  reinterpreted — a stale schema feeding the Trainer wrong knobs would be
  a silent performance (or OOM) regression.
* **Keyed by everything that invalidates a measurement**: device kind
  (block sizes that win on v5e lose on v4), jax version (compiler
  changes), model signature (a different architecture is a different
  search), bucket (scan_k that wins at b1 loses at b8).
* **Replicated read path.** Multi-host consumers read through
  :meth:`TuningStore.load_replicated`: host 0 reads the bytes and
  broadcasts them, so every host adopts IDENTICAL configs even when the
  store lives on host-local disk — hosts disagreeing on scan_k would
  compile different scan lengths and deadlock the first collective.
"""

from __future__ import annotations

import json
import logging
import os
import time
from typing import Dict, Optional

from deepinteract_tpu.robustness import artifacts
from deepinteract_tpu.tuning.space import TrialConfig

logger = logging.getLogger(__name__)

STORE_KIND = "tuning-store"

# 2 (r6): model_signature dropped its compute_dtype suffix when the dtype
# became a tunable knob (tuning/space.py) — entry keys changed format, so
# v1 stores must be rejected loudly (re-run cli.tune), not silently
# unmatched with their tuned knobs reverting to defaults.
SCHEMA_VERSION = 2

DEFAULT_STORE_BASENAME = "tuning_store.json"


class StoreSchemaError(ValueError):
    """The on-disk store's schema_version is not ours."""


def entry_key(device_kind: str, jax_version: str, model_signature: str,
              bucket: str) -> str:
    return f"{device_kind}|{jax_version}|{model_signature}|{bucket}"


def runtime_key(model_signature: str, bucket: str) -> str:
    """The entry key for THIS process's device + jax version."""
    import jax

    return entry_key(jax.devices()[0].device_kind, jax.__version__,
                     model_signature, bucket)


class TuningStore:
    """Load/modify/save wrapper over the schema above. All mutation goes
    through :meth:`put` + :meth:`save`; readers use :meth:`get` /
    :meth:`best_config`."""

    def __init__(self, path: str):
        self.path = path
        self.data: Dict = {
            "schema_version": SCHEMA_VERSION,
            "created": time.time(),
            "entries": {},
        }

    # -- I/O ---------------------------------------------------------------

    @classmethod
    def load(cls, path: str) -> "TuningStore":
        """Read an existing store; raises StoreSchemaError on a version
        mismatch, :class:`~deepinteract_tpu.robustness.artifacts.
        CorruptArtifact` when the bytes fail their integrity sidecar (or
        verified bytes fail to parse), and OSError on a missing file. A
        sidecar-less store from an older run loads unverified (its JSON
        parse errors are still surfaced as CorruptArtifact so every
        caller handles ONE corruption type)."""
        raw = artifacts.verify_read(path, kind=STORE_KIND,
                                    require_sidecar=False)
        try:
            data = json.loads(raw.decode("utf-8"))
        except (UnicodeDecodeError, ValueError) as exc:
            raise artifacts.CorruptArtifact(path, f"not JSON: {exc}")
        return cls._from_payload(path, data)

    @classmethod
    def _from_payload(cls, path: str, data: Dict) -> "TuningStore":
        version = data.get("schema_version")
        if version != SCHEMA_VERSION:
            raise StoreSchemaError(
                f"tuning store {path}: schema_version {version!r} != "
                f"supported {SCHEMA_VERSION}; re-run cli.tune to regenerate"
            )
        if not isinstance(data.get("entries"), dict):
            raise ValueError(f"tuning store {path}: malformed 'entries'")
        store = cls(path)
        store.data = data
        return store

    @classmethod
    def load_or_create(cls, path: str) -> "TuningStore":
        """A corrupt store is quarantined and the search RESTARTS from an
        empty store (re-measuring costs minutes; adopting garbage knobs
        silently regresses every consumer). Schema mismatches still raise
        — they mean the caller must re-tune deliberately, not blindly."""
        directory = os.path.dirname(os.path.abspath(path))
        artifacts.sweep_tmp(directory, prefix=os.path.basename(path))
        if os.path.exists(path):
            try:
                return cls.load(path)
            except artifacts.CorruptArtifact as exc:
                artifacts.quarantine(path, STORE_KIND, str(exc))
                logger.error("tuning store %s was corrupt; restarting the "
                             "search from an empty store", path)
        return cls(path)

    @classmethod
    def load_replicated(cls, path: str) -> Optional["TuningStore"]:
        """Multi-host-safe read: process 0 reads AND integrity-verifies
        (or fails) and broadcasts the bytes; every host parses the SAME
        payload. Returns None when the store does not exist on host 0 —
        or was corrupt there, in which case host 0 quarantines it and
        every host identically degrades to untuned defaults (the
        broadcast of the fallback decision, not the broken bytes).
        Schema errors still raise — on all hosts, identically."""
        import jax

        if jax.process_count() <= 1:
            if not os.path.exists(path):
                return None
            try:
                return cls.load(path)
            except artifacts.CorruptArtifact as exc:
                artifacts.quarantine(path, STORE_KIND, str(exc))
                logger.error("tuning store %s was corrupt; consumers fall "
                             "back to untuned defaults", path)
                return None
        import numpy as np
        from jax.experimental import multihost_utils

        raw = b""
        if jax.process_index() == 0 and os.path.exists(path):
            try:
                raw = artifacts.verify_read(path, kind=STORE_KIND,
                                            require_sidecar=False)
                # Sidecar-less legacy bytes pass verify_read unverified —
                # parse-check them HERE, before the broadcast, so a torn
                # legacy store degrades on every host (empty broadcast)
                # instead of crashing them all in the shared json.loads.
                json.loads(raw.decode("utf-8"))
            except (artifacts.ArtifactError, UnicodeDecodeError,
                    ValueError) as exc:
                artifacts.quarantine(path, STORE_KIND, str(exc))
                logger.error("tuning store %s was corrupt on host 0; every "
                             "host falls back to untuned defaults", path)
                raw = b""
        # Length-prefixed fixed-width broadcast (broadcast_one_to_all needs
        # same-shape arrays on every host).
        n = multihost_utils.broadcast_one_to_all(
            np.asarray([len(raw)], dtype=np.int64))
        size = int(n[0])
        if size == 0:
            return None
        buf = np.zeros(size, dtype=np.uint8)
        if jax.process_index() == 0:
            buf[:] = np.frombuffer(raw, dtype=np.uint8)
        buf = np.asarray(multihost_utils.broadcast_one_to_all(buf),
                         dtype=np.uint8)
        data = json.loads(bytes(buf.tobytes()).decode("utf-8"))
        return cls._from_payload(path, data)

    def save(self) -> None:
        """Atomic whole-file rewrite + integrity sidecar
        (robustness/artifacts.py): a kill mid-save leaves the previous
        version intact — never a torn file — and a later reader can
        verify the bytes before adopting any knob."""
        artifacts.atomic_write_artifact(
            self.path,
            json.dumps(self.data, indent=1, sort_keys=True),
            STORE_KIND, version=SCHEMA_VERSION)

    # -- entries -----------------------------------------------------------

    def put(self, key: str, entry: Dict) -> None:
        self.data["entries"][key] = entry

    def get(self, key: str) -> Optional[Dict]:
        return self.data["entries"].get(key)

    def keys(self):
        return list(self.data["entries"])

    def best_config(self, model_signature: str, bucket: str,
                    ) -> Optional[TrialConfig]:
        """The winning TrialConfig for this process's device/jax version,
        or None when nothing was tuned for that key."""
        entry = self.get(runtime_key(model_signature, bucket))
        if entry is None or "config" not in entry:
            return None
        return TrialConfig.from_dict(entry["config"])

    def best_entry_any_bucket(self, model_signature: str) -> Optional[Dict]:
        """Fallback lookup: any bucket's entry for this device + model —
        used by consumers whose active bucket was never tuned (adopting a
        neighboring bucket's remat/scan_chunks beats hardcoded guesses;
        scan_k transfers less well, which callers note when they fall
        back)."""
        import jax

        prefix = (f"{jax.devices()[0].device_kind}|{jax.__version__}|"
                  f"{model_signature}|")
        for key, entry in sorted(self.data["entries"].items()):
            if key.startswith(prefix):
                return entry
        return None


def default_store_path(ckpt_dir: Optional[str]) -> str:
    """Where the store lives when ``--tuning_store`` is unset: next to the
    checkpoints (the run's durable artifact directory), falling back to
    the working directory."""
    return os.path.join(ckpt_dir or ".", DEFAULT_STORE_BASENAME)
