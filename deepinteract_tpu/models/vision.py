"""DeepLabV3+ alternative interaction decoder (NHWC, XLA convs).

Reimplements the reference's alternative 2D decoder
(``project/utils/vision_modules.py``: ResNet encoder :1-220, ASPP with
separable atrous convs :288-430, DeepLabV3PlusDecoder :433-522,
DeepLabV3Plus assembly :525-609; selected by
``--num_interact_layers`` routing in ``LitGINI.build_interaction_module``,
deepinteract_modules.py:1626-1650) as an idiomatic flax/TPU stack:

* NHWC layout end to end (TPU conv native), bilinear ``jax.image.resize``
  instead of transposed convs, and static shapes throughout.
* A ResNet-34-style basic-block encoder built from scratch (the reference
  wraps torchvision's resnet34) with the last stage dilated (stride 1,
  dilation 2) for output stride 16, matching ``make_dilated``
  (vision_modules.py:174-199).
* Pair-map masking: the interaction map is padded to shape buckets, so all
  normalization statistics are computed over valid positions only, with the
  mask max-pooled alongside each downsampling (no reference equivalent —
  the reference runs on unpadded maps).
* Normalization is masked instance norm rather than BatchNorm2d: batch
  size is 1 complex per device in the reference regime, where BatchNorm's
  per-feature-map statistics degenerate to instance statistics anyway, and
  instance norm keeps train/eval behavior identical under jit.
* Odd input sizes: the input is padded up to a multiple of the output
  stride and logits are sliced back (the reference slices after upsampling,
  vision_modules.py:211-217, 280-285).

The final positive-class bias starts at -7 like the dilated decoder
(deepinteract_modules.py:1224-1226) so untrained positives sit at ~1e-3.
"""

from __future__ import annotations

import dataclasses
from typing import Any, Optional, Sequence, Tuple

import jax
import jax.numpy as jnp
from flax import linen as nn

from deepinteract_tpu.models import policy
from deepinteract_tpu.models.decoder import InstanceNorm
from deepinteract_tpu.models.policy import FLOAT32, OUTPUT_DTYPE, STATS_DTYPE
from deepinteract_tpu.models.stem import DeepLabStemConv, PairFactors


@dataclasses.dataclass(frozen=True)
class DeepLabConfig:
    """Defaults mirror the reference assembly (vision_modules.py:563-576):
    resnet34 encoder, output stride 16, ASPP rates (12, 24, 36), 256
    decoder channels, 2 classes."""

    in_channels: int = 256  # 2 * GNN hidden
    num_classes: int = 2
    # Encoder backbone. The reference's DeepLabV3Plus routes either
    # torchvision resnet34 or ANY timm model via TimmUniversalEncoder
    # (vision_modules.py:525-609); the TPU-native equivalent is a
    # from-scratch encoder zoo: 'resnet18'/'resnet34' (basic blocks) and
    # 'resnet50' (bottleneck blocks). stage_channels/stage_blocks derive
    # from the name when left at the resnet34 defaults.
    encoder_name: str = "resnet34"
    stem_channels: int = 64
    # None = derive from encoder_name (ENCODER_ZOO); explicit values always
    # win, whatever they are.
    stage_channels: Optional[Sequence[int]] = None
    stage_blocks: Optional[Sequence[int]] = None
    aspp_rates: Sequence[int] = (12, 24, 36)
    decoder_channels: int = 256
    high_res_channels: int = 48  # 1x1-projected skip width (DeepLab standard)
    # 16 (reference default, vision_modules.py:567) or 8. os-16 dilates the
    # final stage (stride 1, dilation 2); os-8 dilates the last TWO stages
    # (dilations 2 and 4) and the decoder upsamples 2x instead of 4x to
    # meet the 1/4-scale skip — ``make_dilated``, vision_modules.py:99-110
    # and the os-dependent scale factor at :256.
    output_stride: int = 16
    dropout_rate: float = 0.2

    # Rematerialize encoder blocks in backward (same flag/semantics as
    # DecoderConfig.remat; nn.remat preserves the param tree).
    remat: bool = False
    # Activation/conv compute dtype ('float32' | 'bfloat16') — the DeepLab
    # leg of the model-wide dtype policy (models/policy.py). Params and
    # instance-norm statistics stay float32; logits are float32.
    compute_dtype: str = "float32"

    @property
    def dtype(self):
        return policy.compute_dtype(self.compute_dtype)

    def __post_init__(self):
        if self.output_stride not in (8, 16):
            raise ValueError("DeepLabConfig.output_stride must be 8 or 16")
        if self.encoder_name not in ENCODER_ZOO:
            raise ValueError(
                f"unknown encoder {self.encoder_name!r}; "
                f"choose from {sorted(ENCODER_ZOO)}"
            )
        # Derive stage shapes from the encoder name only where the caller
        # left them None — explicitly passed values always win.
        _, zoo_blocks, zoo_channels = ENCODER_ZOO[self.encoder_name]
        if self.stage_blocks is None:
            object.__setattr__(self, "stage_blocks", zoo_blocks)
        if self.stage_channels is None:
            object.__setattr__(self, "stage_channels", zoo_channels)


def _pool_mask(mask: jnp.ndarray, factor: int) -> jnp.ndarray:
    """Downsample a [B, H, W] validity mask by max-pooling: a coarse cell is
    valid if any covered fine cell is."""
    if factor == 1:
        return mask
    return nn.max_pool(
        mask[..., None], (factor, factor), strides=(factor, factor)
    )[..., 0]


class ConvNormAct(nn.Module):
    features: int
    kernel: int = 3
    stride: int = 1
    dilation: int = 1
    use_act: bool = True
    dtype: Any = FLOAT32

    @nn.compact
    def __call__(self, x, mask=None):
        x = nn.Conv(
            self.features, (self.kernel, self.kernel),
            strides=(self.stride, self.stride),
            kernel_dilation=(self.dilation, self.dilation),
            padding="SAME", use_bias=False, dtype=self.dtype,
        )(x)
        x = InstanceNorm(self.features)(x, mask)
        return nn.relu(x) if self.use_act else x


class SeparableConv(nn.Module):
    """Depthwise 3x3 (optionally atrous) + pointwise 1x1 — the ASPP
    separable convolution (vision_modules.py ``SeparableConv2d``)."""

    features: int
    dilation: int = 1
    dtype: Any = FLOAT32

    @nn.compact
    def __call__(self, x, mask=None):
        c_in = x.shape[-1]
        x = nn.Conv(
            c_in, (3, 3), feature_group_count=c_in,
            kernel_dilation=(self.dilation, self.dilation),
            padding="SAME", use_bias=False, dtype=self.dtype,
        )(x)
        x = nn.Conv(self.features, (1, 1), use_bias=False, dtype=self.dtype)(x)
        x = InstanceNorm(self.features)(x, mask)
        return nn.relu(x)


class BasicBlock(nn.Module):
    """ResNet-34 basic block: two 3x3 convs + identity/projection shortcut.

    ``use_projection`` can force the 1x1 shortcut even at stride 1: the
    reference's os-8 ``replace_strides_with_dilation`` keeps the downsample
    conv (at stride 1) wherever the os-16 structure had one, so the param
    tree — and checkpoint compatibility — is independent of output stride.
    """

    features: int
    stride: int = 1
    dilation: int = 1
    use_projection: Optional[bool] = None
    dtype: Any = FLOAT32

    @nn.compact
    def __call__(self, x, mask=None):
        identity = x
        dt = self.dtype
        y = ConvNormAct(self.features, 3, self.stride, self.dilation,
                        dtype=dt)(x, mask)
        y = ConvNormAct(self.features, 3, 1, self.dilation, use_act=False,
                        dtype=dt)(y, mask)
        project = (
            self.use_projection if self.use_projection is not None
            else self.stride != 1 or x.shape[-1] != self.features
        )
        if project:
            identity = ConvNormAct(self.features, 1, self.stride,
                                   use_act=False, dtype=dt)(x, mask)
        return nn.relu(y + identity)


class BottleneckResBlock(nn.Module):
    """ResNet-50-style bottleneck: 1x1 reduce -> 3x3 -> 1x1 expand with
    identity/projection shortcut (the torchvision Bottleneck the
    reference's universal encoder pulls in for deeper backbones)."""

    features: int  # expanded output width
    stride: int = 1
    dilation: int = 1
    use_projection: Optional[bool] = None
    dtype: Any = FLOAT32

    @nn.compact
    def __call__(self, x, mask=None):
        identity = x
        dt = self.dtype
        mid = self.features // 4
        # Stride on the first 1x1 (ResNet v1 convention): the downsampled
        # mask the encoder passes then matches every norm in the block
        # (stride on the 3x3, v1.5, would hand the first norm a mask at
        # the wrong scale). CHECKPOINT-IMPORT CAVEAT (ADVICE r4 item 2):
        # torchvision/timm resnet50 — what the reference's
        # TimmUniversalEncoder loads — is v1.5 (stride on the 3x3). Param
        # shapes are IDENTICAL, so v1.5 weights would load shape-clean here
        # yet compute different activations at every strided bottleneck.
        # The torch importer maps only the dilated-decoder checkpoint
        # family (training/import_torch.py) — it has NO DeepLab-encoder
        # mapping, so a v1.5 import cannot happen silently; anyone adding
        # one must re-layout the stride onto the 3x3 (and rescale the
        # masks) first. Our from-scratch resnet50 trains under v1.
        y = ConvNormAct(mid, 1, self.stride, dtype=dt)(x, mask)
        y = ConvNormAct(mid, 3, 1, self.dilation, dtype=dt)(y, mask)
        y = ConvNormAct(self.features, 1, use_act=False, dtype=dt)(y, mask)
        project = (
            self.use_projection if self.use_projection is not None
            else self.stride != 1 or x.shape[-1] != self.features
        )
        if project:
            identity = ConvNormAct(self.features, 1, self.stride,
                                   use_act=False, dtype=dt)(x, mask)
        return nn.relu(y + identity)


# encoder_name -> (block class name, stage_blocks, stage_channels). Class
# resolved lazily (classes are defined above/below this table).
ENCODER_ZOO = {
    "resnet18": ("basic", (2, 2, 2, 2), (64, 128, 256, 512)),
    "resnet34": ("basic", (3, 4, 6, 3), (64, 128, 256, 512)),
    "resnet50": ("bottleneck", (3, 4, 6, 3), (256, 512, 1024, 2048)),
    "resnet101": ("bottleneck", (3, 4, 23, 3), (256, 512, 1024, 2048)),
    "resnet152": ("bottleneck", (3, 8, 36, 3), (256, 512, 1024, 2048)),
}


class StemConvNorm(nn.Module):
    """The encoder's 7x7/2 stem conv + masked instance norm + relu.

    Functionally the old ``ConvNormAct(stem_channels, 7, 2)`` — child names
    (``Conv_0``/``InstanceNorm_0``) and param shapes are preserved — but
    the conv is :class:`~deepinteract_tpu.models.stem.DeepLabStemConv`,
    which also accepts ``PairFactors`` and then computes the stride-2 conv
    without materializing the 2C pair tensor."""

    features: int
    dtype: Any = FLOAT32

    @nn.compact
    def __call__(self, x, mask):
        y = DeepLabStemConv(self.features, kernel_size=7, stride=2,
                            dtype=self.dtype, name="Conv_0")(x)
        y = InstanceNorm(self.features, name="InstanceNorm_0")(y, mask)
        return nn.relu(y)


class ResNetEncoder(nn.Module):
    """Stem + 4 residual stages; returns (1/4-scale skip, 1/16-scale
    deep features) — the two taps DeepLabV3+ consumes
    (vision_modules.py:201-219). The block family comes from
    ``cfg.encoder_name`` (see ENCODER_ZOO)."""

    cfg: DeepLabConfig

    @nn.compact
    def __call__(self, x, mask):
        cfg = self.cfg
        dt = cfg.dtype
        # Stem: 7x7/2 + 3x3/2 max pool (torchvision resnet layout). The
        # stem block accepts the materialized pair tensor OR PairFactors
        # (the factorized interaction stem, models/stem.py) with one param
        # tree; the explicit name keeps the historical
        # ConvNormAct_0/{Conv_0, InstanceNorm_0} checkpoint scope.
        m2 = _pool_mask(mask, 2)
        x = StemConvNorm(cfg.stem_channels, dtype=dt,
                         name="ConvNormAct_0")(x, m2)
        x = nn.max_pool(x, (3, 3), strides=(2, 2), padding="SAME")
        m4 = _pool_mask(mask, 4)
        # Max pooling at the pad frontier picks up valid neighbors, making
        # padded pixels nonzero; re-zero before the stage convs read them
        # (every masked InstanceNorm re-zeroes after its conv, so this is
        # the one spot where unmasked values could smear into the valid
        # region).
        x = x * m4[..., None].astype(x.dtype)

        skip = None
        m = m4
        scale = 4
        base_block = (
            BottleneckResBlock
            if ENCODER_ZOO[cfg.encoder_name][0] == "bottleneck" else BasicBlock
        )
        block_cls = nn.remat(base_block) if cfg.remat else base_block
        # Stage (stride, dilation) patterns (make_dilated,
        # vision_modules.py:99-110): os-16 dilates the final stage, os-8
        # runs the last two stages at stride 1 with dilations 2 and 4.
        plan16 = ((1, 1), (2, 1), (2, 1), (1, 2))
        if cfg.output_stride == 8:
            plan = ((1, 1), (2, 1), (1, 2), (1, 4))
        else:
            plan = plan16
        for s, (feats, blocks) in enumerate(zip(cfg.stage_channels, cfg.stage_blocks)):
            stride, dilation = plan[s]
            if stride == 2:
                scale *= 2
                m = _pool_mask(mask, scale)
            for b in range(blocks):
                # Projection shortcuts follow the os-16 structure so both
                # output strides share one param tree (see BasicBlock).
                proj = (
                    (plan16[s][0] != 1 or x.shape[-1] != feats)
                    if b == 0 else False
                )
                x = block_cls(
                    feats, stride=stride if b == 0 else 1, dilation=dilation,
                    use_projection=proj, dtype=dt, name=f"stage{s}_block{b}",
                )(x, m)
            if s == 0:
                skip = x  # 1/4 scale high-res tap
        return skip, m4, x, m


class ASPP(nn.Module):
    """Atrous spatial pyramid pooling: 1x1 + three separable atrous convs +
    masked global pooling, concatenated and projected
    (vision_modules.py:288-430)."""

    cfg: DeepLabConfig

    @nn.compact
    def __call__(self, x, mask, train: bool):
        cfg = self.cfg
        dt = cfg.dtype
        ch = cfg.decoder_channels
        branches = [ConvNormAct(ch, 1, dtype=dt)(x, mask)]
        for rate in cfg.aspp_rates:
            branches.append(SeparableConv(ch, dilation=rate, dtype=dt)(x, mask))
        # Masked global-average pooling branch; the spatial mean
        # accumulates in float32 (policy stats dtype).
        m = mask[..., None].astype(STATS_DTYPE)
        count = jnp.maximum(jnp.sum(m, axis=(1, 2), keepdims=True), 1.0)
        pooled = (jnp.sum(x.astype(STATS_DTYPE) * m, axis=(1, 2),
                          keepdims=True) / count).astype(x.dtype)
        pooled = nn.relu(nn.Conv(ch, (1, 1), use_bias=False,
                                 dtype=dt)(pooled))
        branches.append(jnp.broadcast_to(pooled, x.shape[:-1] + (ch,)))

        y = jnp.concatenate(branches, axis=-1)
        y = ConvNormAct(ch, 1, dtype=dt)(y, mask)
        y = SeparableConv(ch, dtype=dt)(y, mask)
        y = nn.Dropout(self.cfg.dropout_rate, deterministic=not train)(y)
        return y


class DeepLabDecoder(nn.Module):
    """Drop-in alternative to ``InteractionDecoder``: [B, H, W, 2C] padded
    interaction tensor + [B, H, W] pair mask -> [B, H, W, num_classes]."""

    cfg: DeepLabConfig

    @nn.compact
    def __call__(self, x, mask=None, train: bool = False):
        cfg = self.cfg
        dt = cfg.dtype
        factored = isinstance(x, PairFactors)
        if factored:
            # Factorized interaction stem (models/stem.py): per-chain
            # features/masks; the 2C pair tensor is never materialized —
            # the stem conv consumes the factors directly and the first
            # full-resolution map is the stride-2 stem output.
            f1, f2 = x.feats1, x.feats2
            b, h = f1.shape[0], f1.shape[1]
            w = f2.shape[1]
            m1 = (jnp.ones((b, h), dt) if x.mask1 is None
                  else x.mask1.astype(dt))
            m2 = (jnp.ones((b, w), dt) if x.mask2 is None
                  else x.mask2.astype(dt))
        else:
            b, h, w, _ = x.shape
            if mask is None:
                mask = jnp.ones((b, h, w), dtype=dt)
            mask = mask.astype(dt)

        # Pad to a multiple of the output stride; slice logits back at the
        # end (reference slices after upsampling, vision_modules.py:211-217).
        os_ = cfg.output_stride
        ph = (-h) % os_
        pw = (-w) % os_
        if factored:
            if ph or pw:
                f1 = jnp.pad(f1, ((0, 0), (0, ph), (0, 0)))
                m1 = jnp.pad(m1, ((0, 0), (0, ph)))
                f2 = jnp.pad(f2, ((0, 0), (0, pw), (0, 0)))
                m2 = jnp.pad(m2, ((0, 0), (0, pw)))
            # The [B, H, W] pair mask is cheap (no channel dim) and drives
            # every downstream pooled-mask statistic exactly as before. A
            # caller-passed mask is honored (it must be a subset of the
            # chain masks' outer product — the stem conv itself can only
            # factorize the separable chain-mask form); None derives it.
            if mask is not None:
                if ph or pw:
                    mask = jnp.pad(mask.astype(dt),
                                   ((0, 0), (0, ph), (0, pw)))
                else:
                    mask = mask.astype(dt)
            else:
                mask = m1[:, :, None] * m2[:, None, :]
            enc_in = PairFactors(f1.astype(dt), f2.astype(dt), m1, m2,
                                 shard_pair=x.shard_pair)
        else:
            if ph or pw:
                x = jnp.pad(x, ((0, 0), (0, ph), (0, pw), (0, 0)))
                mask = jnp.pad(mask, ((0, 0), (0, ph), (0, pw)))
            enc_in = x.astype(dt) * mask[..., None]

        skip, m4, deep, m16 = ResNetEncoder(cfg)(enc_in, mask)
        y = ASPP(cfg)(deep, m16, train)

        # Upsample x4, fuse with the 1x1-projected high-res skip, refine.
        # Mask-renormalized bilinear (resize y*mask and mask separately,
        # then divide): plain bilinear would pull masked-out zeros into
        # valid cells near the pad frontier, making logits depend on the
        # padding bucket — the unpadded reference has no such frontier.
        y = _masked_resize(y, m16, (skip.shape[1], skip.shape[2]))
        hi = ConvNormAct(cfg.high_res_channels, 1, dtype=dt)(skip, m4)
        y = jnp.concatenate([y * m4[..., None].astype(y.dtype), hi], axis=-1)
        y = SeparableConv(cfg.decoder_channels, dtype=dt)(y, m4)
        y = SeparableConv(cfg.decoder_channels, dtype=dt)(y, m4)

        # Segmentation head: 1x1 to classes in float32 (the policy's
        # output dtype), then upsample x4 to input size.
        logits = nn.Conv(
            cfg.num_classes, (1, 1),
            bias_init=_pos_bias_init(cfg.num_classes),
        )(y.astype(OUTPUT_DTYPE))
        full = (h + ph, w + pw)
        logits = _masked_resize(logits, m4, full)
        logits = logits[:, :h, :w, :]
        return logits * mask[:, :h, :w, None].astype(logits.dtype)


def _masked_resize(y: jnp.ndarray, mask: jnp.ndarray, hw) -> jnp.ndarray:
    """Bilinear upsample that ignores padded cells: resize the masked
    values and the mask, then renormalize by the resized mask (zero where
    no valid support). Padded buckets thus reproduce unpadded outputs."""
    b, _, _, c = y.shape
    m = mask[..., None].astype(y.dtype)
    num = jax.image.resize(y * m, (b, hw[0], hw[1], c), method="bilinear")
    den = jax.image.resize(m, (b, hw[0], hw[1], 1), method="bilinear")
    return jnp.where(den > 1e-6, num / jnp.maximum(den, 1e-6), 0.0)


def _pos_bias_init(num_classes: int):
    """Positive-class logit bias -7 (deepinteract_modules.py:1224-1226)."""

    def init(key, shape, dtype=OUTPUT_DTYPE):
        del key
        bias = jnp.zeros(shape, dtype)
        return bias.at[-1].set(-7.0) if num_classes == 2 else bias

    return init
