"""The full siamese network: Geometric Transformer encoder x2 -> interaction
tensor -> dense 2D decoder -> per-pair contact logits.

Reference: ``LitGINI`` (project/utils/deepinteract_modules.py:1478-2236) —
here only the network itself; training/optimization/metrics live in
:mod:`deepinteract_tpu.training`. Both chains share one set of GNN weights
(siamese; ``shared_step`` applies the same module to graph1 and graph2,
deepinteract_modules.py:1687-1691).
"""

from __future__ import annotations

import dataclasses

import jax
import jax.numpy as jnp
from flax import linen as nn

from deepinteract_tpu import constants as C
from deepinteract_tpu.data.graph import PairedComplex, ProteinGraph
from deepinteract_tpu.models.decoder import DecoderConfig, InteractionDecoder
from deepinteract_tpu.models.geometric_transformer import GeometricTransformer, GTConfig
from deepinteract_tpu.models.interaction import interaction_tensor, pair_mask
from deepinteract_tpu.models.layers import GODense
from deepinteract_tpu.models.policy import validate_compute_dtype
from deepinteract_tpu.models.stem import PairFactors, validate_stem
from deepinteract_tpu.models.vision import DeepLabConfig, DeepLabDecoder


@dataclasses.dataclass(frozen=True)
class ModelConfig:
    """Full-network hyperparameters (defaults follow LitGINI defaults,
    deepinteract_modules.py:1481-1489)."""

    num_node_input_feats: int = C.NUM_NODE_FEATS
    gnn: GTConfig = dataclasses.field(default_factory=GTConfig)
    decoder: DecoderConfig = dataclasses.field(default_factory=DecoderConfig)
    gnn_layer_type: str = "geotran"  # 'geotran' | 'gcn'
    # 'dilated' = SE-ResNet decoder (reference default); 'deeplab' = the
    # DeepLabV3+ alternative (LitGINI.build_interaction_module routing,
    # deepinteract_modules.py:1626-1650).
    interact_module_type: str = "dilated"
    num_classes: int = C.NUM_CLASSES
    # Context parallelism: annotate the L1 x L2 interaction map for sharding
    # over the mesh's 'pair' axis (requires an active mesh context). This is
    # the distributed form of the reference's 256x256 subsequencing tiles
    # (deepinteract_utils.py:122-155), SURVEY.md §2.6.
    shard_pair_map: bool = False
    # Long-context tier: decode the pair map in tile_size x tile_size blocks
    # via lax.scan so the full interaction tensor is never materialized
    # (reference subsequencing, deepinteract_utils.py:122-155,184-308 — see
    # models/tiled.py). Engages only when the padded map exceeds one tile.
    tile_pair_map: bool = False
    tile_size: int = C.PAIR_MAP_TILE
    deeplab: DeepLabConfig = dataclasses.field(default_factory=DeepLabConfig)
    # How the decoders consume the encoder output (models/stem.py):
    # 'factorized' (default) computes the first decoder layer from
    # per-chain features without materializing the [B, L1, L2, 2C]
    # interaction tensor — ~256 MB of f32 activations per sample at the
    # L=512 bucket; 'materialized' builds the full tensor (kept for
    # parity testing / A-B benchmarking — both share one param tree).
    interaction_stem: str = "factorized"
    # End-to-end compute-dtype policy (models/policy.py). None keeps the
    # sub-configs' own settings (heterogeneous precision is allowed for
    # A/Bs); 'float32'/'bfloat16' is pushed into the encoder, decoder AND
    # DeepLab configs — params, norm statistics, logits and loss stay
    # float32 either way, so no loss scaling is needed on TPU.
    compute_dtype: "str | None" = None

    def __post_init__(self):
        validate_stem(self.interaction_stem)
        if self.compute_dtype is not None:
            validate_compute_dtype(self.compute_dtype)
            if self.gnn.compute_dtype != self.compute_dtype:
                object.__setattr__(
                    self, "gnn", dataclasses.replace(
                        self.gnn, compute_dtype=self.compute_dtype))
            if self.decoder.compute_dtype != self.compute_dtype:
                object.__setattr__(
                    self, "decoder", dataclasses.replace(
                        self.decoder, compute_dtype=self.compute_dtype))
            if self.deeplab.compute_dtype != self.compute_dtype:
                object.__setattr__(
                    self, "deeplab", dataclasses.replace(
                        self.deeplab, compute_dtype=self.compute_dtype))
        updates = {}
        if self.decoder.in_channels != 2 * self.gnn.hidden:
            updates["in_channels"] = 2 * self.gnn.hidden
        if self.decoder.num_classes != self.num_classes:
            updates["num_classes"] = self.num_classes
        if updates:
            object.__setattr__(
                self, "decoder", dataclasses.replace(self.decoder, **updates)
            )
        if self.deeplab.in_channels != 2 * self.gnn.hidden or (
            self.deeplab.num_classes != self.num_classes
        ):
            object.__setattr__(
                self, "deeplab",
                dataclasses.replace(
                    self.deeplab,
                    in_channels=2 * self.gnn.hidden,
                    num_classes=self.num_classes,
                ),
            )


class GCNStack(nn.Module):
    """Plain graph-convolution alternative (``--gnn_layer_type gcn``,
    LitGINI.build_gnn_module/gnn_forward, deepinteract_modules.py:1591-1625,
    1660-1679): DGL ``GraphConv`` with symmetric degree norm, edge-weighted by
    the min-max-normalized squared distance (edge feature column 1), no
    activation between layers."""

    cfg: GTConfig
    num_layers: int = 2

    @nn.compact
    def __call__(self, graph: ProteinGraph, node_feats, train: bool = False):
        w = graph.edge_feats[..., C.EDGE_WEIGHT] * graph.edge_mask()  # [B,N,K]
        e_mask = graph.edge_mask().astype(node_feats.dtype)
        # DGL GraphConv(norm='both') normalizes by *unweighted* edge-count
        # degrees (edge_weight only scales messages; weighted-degree
        # normalization would require EdgeWeightNorm), and adds a bias.
        deg_out = jnp.sum(e_mask, axis=-1)  # out-degree at the row owner

        def count_in(m_b, nbr_b):
            return jax.ops.segment_sum(m_b.reshape(-1), nbr_b.reshape(-1),
                                       num_segments=m_b.shape[0])

        deg_in = jax.vmap(count_in)(e_mask, graph.nbr_idx)
        norm_src = jax.lax.rsqrt(jnp.maximum(deg_out, 1e-9))
        norm_dst = jax.lax.rsqrt(jnp.maximum(deg_in, 1e-9))

        h = node_feats
        for i in range(self.num_layers):
            h = GODense(self.cfg.hidden, use_bias=False, name=f"gcn_{i}")(h)
            hn = h * norm_src[..., None]

            def scatter(h_b, w_b, nbr_b):
                contrib = h_b[:, None, :] * w_b[..., None]  # [N,K,C] from src rows
                return jax.ops.segment_sum(
                    contrib.reshape(-1, h_b.shape[-1]), nbr_b.reshape(-1),
                    num_segments=h_b.shape[0],
                )

            h = jax.vmap(scatter)(hn, w, graph.nbr_idx) * norm_dst[..., None]
            h = h + self.param(f"gcn_bias_{i}", nn.initializers.zeros, (self.cfg.hidden,))
            h = h * graph.node_mask[..., None]
        return h, None


class DeepInteract(nn.Module):
    """Siamese GT + interaction decoder. Returns [B, L1, L2, num_classes]
    logits plus (optionally) learned node representations."""

    cfg: ModelConfig

    def setup(self):
        gnn_cfg = self.cfg.gnn
        if self.cfg.num_node_input_feats != gnn_cfg.hidden:
            self.node_in_embedding = GODense(gnn_cfg.hidden, use_bias=False,
                                             dtype=gnn_cfg.dtype)
        else:
            self.node_in_embedding = None
        if self.cfg.gnn_layer_type == "gcn":
            self.gnn = GCNStack(gnn_cfg, num_layers=gnn_cfg.num_layers)
        else:
            self.gnn = GeometricTransformer(gnn_cfg)
        if self.cfg.interact_module_type == "deeplab":
            self.decoder = DeepLabDecoder(self.cfg.deeplab)
        else:
            self.decoder = InteractionDecoder(self.cfg.decoder)

    def encode(self, graph: ProteinGraph, train: bool = False):
        """Shared-weight chain encoder (siamese leg)."""
        x = jnp.asarray(graph.node_feats)
        if self.node_in_embedding is not None:
            x = self.node_in_embedding(x)
        node_feats, edge_feats = self.gnn(graph, x, train=train)
        return node_feats, edge_feats

    def decode(self, feats1, feats2, mask1, mask2, train: bool = False):
        """Interaction stem + decoder over already-encoded chain features:
        the second phase of the split forward. ``__call__`` is exactly
        ``decode(encode(g1), encode(g2))``, so the split-phase serving path
        (screening's cached embeddings — ``serving/engine.py``) matches the
        monolithic forward by construction. ``feats1``/``feats2`` are
        ``[..., L, C]`` encoder outputs, ``mask1``/``mask2`` the ``[..., L]``
        node-validity masks. Inputs are cast to the encoder's compute dtype
        (embeddings cached as float32 round-trip losslessly from bfloat16)."""
        feats1 = jnp.asarray(feats1, dtype=self.cfg.gnn.dtype)
        feats2 = jnp.asarray(feats2, dtype=self.cfg.gnn.dtype)
        l1, l2 = feats1.shape[-2], feats2.shape[-2]
        factorized = self.cfg.interaction_stem == "factorized"
        if self.cfg.tile_pair_map and (
            l1 > self.cfg.tile_size or l2 > self.cfg.tile_size
        ):
            from deepinteract_tpu.models.tiled import tiled_decode

            return tiled_decode(
                self.decoder, feats1, feats2,
                mask1, mask2,
                tile=self.cfg.tile_size, train=train,
                shard_pair_axis=self.cfg.shard_pair_map,
                stem=self.cfg.interaction_stem,
            )
        if factorized:
            # Factorized stem (models/stem.py): the decoder's first layer
            # is computed from per-chain factors — the [B, L1, L2, 2C]
            # interaction tensor is never materialized. The pair mask is
            # built (and, under context parallelism, sharding-annotated)
            # here; the stem annotates its own broadcast output.
            pm = pair_mask(mask1, mask2)
            if self.cfg.shard_pair_map:
                from deepinteract_tpu.models.stem import shard_pair_rows

                pm = shard_pair_rows(pm)
            factors = PairFactors(
                feats1, feats2, mask1, mask2,
                shard_pair=self.cfg.shard_pair_map,
            )
            return self.decoder(factors, pm, train=train)
        pm = pair_mask(mask1, mask2)
        tensor = interaction_tensor(feats1, feats2)
        if self.cfg.shard_pair_map:
            from deepinteract_tpu.models.stem import shard_pair_rows

            tensor = shard_pair_rows(tensor)
            pm = shard_pair_rows(pm)
        return self.decoder(tensor, pm, train=train)

    def __call__(
        self,
        graph1: ProteinGraph,
        graph2: ProteinGraph,
        train: bool = False,
        return_representations: bool = False,
    ):
        feats1, efeats1 = self.encode(graph1, train=train)
        feats2, efeats2 = self.encode(graph2, train=train)
        logits = self.decode(feats1, feats2,
                             graph1.node_mask, graph2.node_mask, train=train)

        if return_representations:
            return logits, {
                "graph1_node_feats": feats1,
                "graph1_edge_feats": efeats1,
                "graph2_node_feats": feats2,
                "graph2_edge_feats": efeats2,
            }
        return logits


def forward_complex(model: DeepInteract, variables, cx: PairedComplex, train=False, rngs=None,
                    mutable=()):
    """Convenience apply() over a PairedComplex."""
    return model.apply(
        variables, cx.graph1, cx.graph2, train=train, rngs=rngs, mutable=list(mutable)
    )
