"""Model layer: Geometric Transformer, interaction decoders, full network."""

from deepinteract_tpu.models.geometric_transformer import GeometricTransformer, GTConfig  # noqa: F401
