"""Blockwise (tiled) pair-map decoding — the long-context tier.

Reference: "subsequencing" (``construct_subsequenced_interact_tensors`` /
``remove_subsequenced_input_padding`` / ``insert_interact_tensor_logits``,
deepinteract_utils.py:122-155,184-236,239-308; orchestrated at
deepinteract_modules.py:1695-1737): chains longer than 256 residues split
into 256-blocks, the cartesian product of blocks runs through the decoder
independently, and per-tile logits are scattered back into the full L1 x L2
map. The reference walks tiles with stateful Python index bookkeeping; here
the tile grid is a static ``lax.scan`` over tile indices:

* the full interaction tensor is never materialized — each scan step slices
  [T, C] node-feature blocks, builds one [T, T, 2C] tile, and decodes it, so
  peak memory is one tile's activations regardless of L1 x L2;
* decoder parameters are broadcast across the scan (``nn.scan``
  ``variable_broadcast='params'``), dropout rngs split per tile;
* semantics match the reference: each tile is decoded as an independent map
  (instance-norm/SE statistics are per-tile, exactly like the reference's
  per-tile decoder passes).

This composes with context parallelism: the scan runs the tile *grid*
sequentially while the mesh's 'pair' axis shards *within* each tile.
"""

from __future__ import annotations

import jax.numpy as jnp
from flax import linen as nn
from jax import lax


def tile_grid(l1: int, l2: int, tile: int) -> tuple:
    if l1 % tile or l2 % tile:
        raise ValueError(
            f"padded chain lengths ({l1}, {l2}) must be multiples of the "
            f"tile size {tile}; pick buckets accordingly"
        )
    return l1 // tile, l2 // tile


def tiled_decode(
    decoder: nn.Module,
    feats1: jnp.ndarray,
    feats2: jnp.ndarray,
    mask1: jnp.ndarray,
    mask2: jnp.ndarray,
    tile: int,
    train: bool = False,
    shard_pair_axis: bool = False,
    stem: str = "materialized",
) -> jnp.ndarray:
    """Decode the [B, L1, L2] pair map in T x T tiles.

    Args:
      decoder: bound ``InteractionDecoder`` submodule (params shared with
        the untiled path).
      feats1, feats2: [B, L1, C], [B, L2, C] encoded node features.
      mask1, mask2:   [B, L1], [B, L2] validity masks.
      shard_pair_axis: context parallelism *within* each tile — annotate
        the tile's row axis for the mesh's 'pair' axis (requires an active
        mesh, like ModelConfig.shard_pair_map's untiled path). The tile
        grid stays a sequential scan; each tile's convs shard across
        devices with XLA inserting the halo exchanges.
      stem: 'factorized' hands the decoder per-tile ``PairFactors`` so
        even the tile's own [T, T, 2C] tensor is never materialized (only
        the first layer's [T, T, num_channels] output is);
        'materialized' builds the tile tensor as before. Same params
        either way (models/stem.py).

    Returns [B, L1, L2, num_classes] logits (padded region zeroed).
    """
    b, l1, c = feats1.shape
    l2 = feats2.shape[1]
    n1, n2 = tile_grid(l1, l2, tile)

    def step(dec: nn.Module, carry, idx):
        ti, tj = idx // n2, idx % n2
        f1 = lax.dynamic_slice_in_dim(feats1, ti * tile, tile, axis=1)
        f2 = lax.dynamic_slice_in_dim(feats2, tj * tile, tile, axis=1)
        m1 = lax.dynamic_slice_in_dim(mask1, ti * tile, tile, axis=1)
        m2 = lax.dynamic_slice_in_dim(mask2, tj * tile, tile, axis=1)
        pm = m1[:, :, None] & m2[:, None, :]
        if shard_pair_axis:
            from deepinteract_tpu.models.stem import shard_pair_rows

            pm = shard_pair_rows(pm)
        if stem == "factorized":
            from deepinteract_tpu.models.stem import PairFactors

            pair = PairFactors(f1, f2, m1, m2, shard_pair=shard_pair_axis)
        else:
            pair = jnp.concatenate(
                [
                    jnp.broadcast_to(f1[:, :, None, :], (b, tile, tile, c)),
                    jnp.broadcast_to(f2[:, None, :, :], (b, tile, tile, c)),
                ],
                axis=-1,
            )
            if shard_pair_axis:
                from deepinteract_tpu.models.stem import shard_pair_rows

                pair = shard_pair_rows(pair)
        logits = dec(pair, pm, train=train)
        return carry, logits

    scan = nn.scan(
        step,
        variable_broadcast="params",
        split_rngs={"params": False, "dropout": True},
        in_axes=0,
        out_axes=0,
    )
    _, tiles = scan(decoder, None, jnp.arange(n1 * n2))
    # [n1*n2, B, T, T, K] -> [B, L1, L2, K]
    k = tiles.shape[-1]
    tiles = tiles.reshape(n1, n2, b, tile, tile, k)
    out = tiles.transpose(2, 0, 3, 1, 4, 5).reshape(b, l1, l2, k)
    if shard_pair_axis:
        from deepinteract_tpu.models.stem import shard_pair_rows

        # Keep the assembled full map row-sharded too: without this the
        # scatter-back gathers every tile onto one device before the
        # caller's softmax/masking, defeating the per-shard decode.
        out = shard_pair_rows(out)
    return out
