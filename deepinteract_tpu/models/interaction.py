"""Interaction-tensor construction: two chains' node features -> pair map.

Reference: ``construct_interact_tensor`` (deepinteract_utils.py:158-172)
concatenates the broadcast (C, L1) and (C, L2) matrices along the channel
dim — ``torch.cat((repeat(x_a), repeat(x_b)), dim=1)`` — into a
(1, 2C, L1, L2) NCHW tensor whose first C channels are chain-1 features.
We produce NHWC ``[B, L1, L2, 2C]`` (TPU conv-native) with the SAME
``[feats1 | feats2]`` channel order: channels [:C] are chain-1 features
broadcast along columns, channels [C:] chain-2 features broadcast along
rows — so checkpoint import (training/import_torch.py) needs no channel
permutation. Padding is inherent — inputs arrive already padded, and the
pair mask (outer product of node masks) travels with the tensor.

This is the MATERIALIZED form. The production default avoids building it
at all: the factorized interaction stem (``models/stem.py``) exploits the
``[f1_i | f2_j]`` structure to compute the decoders' first layer directly
from the per-chain factors — this module remains the parity/A-B reference
and the building block for code that genuinely needs the dense tensor.
"""

from __future__ import annotations

import jax.numpy as jnp


def interaction_tensor(feats1: jnp.ndarray, feats2: jnp.ndarray) -> jnp.ndarray:
    """[B, L1, C] x [B, L2, C] -> [B, L1, L2, 2C]."""
    b, l1, c = feats1.shape
    l2 = feats2.shape[1]
    a = jnp.broadcast_to(feats1[:, :, None, :], (b, l1, l2, c))
    bb = jnp.broadcast_to(feats2[:, None, :, :], (b, l1, l2, c))
    return jnp.concatenate([a, bb], axis=-1)


def pair_mask(node_mask1: jnp.ndarray, node_mask2: jnp.ndarray) -> jnp.ndarray:
    """[B, L1] x [B, L2] -> [B, L1, L2] validity mask."""
    return node_mask1[:, :, None] & node_mask2[:, None, :]
