"""The model-wide dtype policy: the ONE place models/ names a dtype.

End-to-end reduced precision on TPU is a *policy*, not a per-layer flag:
params live in ``param_dtype`` (float32 — optimizer state and checkpoints
never change layout), matmuls/convs/elementwise activations run in
``compute_dtype`` (float32 or bfloat16), and outward-facing tensors
(logits, loss, anything a metric reads) are ``output_dtype`` (float32).
Normalization statistics, softmax accumulators, and other
cancellation-sensitive reductions always accumulate in ``STATS_DTYPE``
(float32) regardless of the compute dtype — that is what makes bf16 safe
without loss scaling on TPU (bf16 shares float32's exponent range, so
only reductions lose precision, and those are pinned here).

Discipline: ``tools/check_dtype_discipline.py`` (a fast-tier AST lint)
forbids hardcoded ``jnp.float32`` / ``jnp.bfloat16`` references anywhere
in ``models/`` outside this module. Model code imports ``STATS_DTYPE`` /
``OUTPUT_DTYPE`` or resolves a :class:`DTypePolicy` instead, so "where
may precision change" has exactly one answer.
"""

from __future__ import annotations

import dataclasses

import jax.numpy as jnp

# float32 anchors. STATS_DTYPE is for accumulation-sensitive reductions
# (norm moments, softmax exp/sums, pooled means); OUTPUT_DTYPE is for
# outward-facing tensors (logits, probabilities, loss inputs). They are
# the same dtype today but name different *reasons* — a future fp64
# debugging policy would split them.
STATS_DTYPE = jnp.float32
OUTPUT_DTYPE = jnp.float32
PARAM_DTYPE = jnp.float32
# The canonical float32 for default module dtypes / initializer
# signatures in models/ (the lint forbids naming jnp.float32 there).
FLOAT32 = jnp.float32

_COMPUTE_DTYPES = {
    "float32": jnp.float32,
    "bfloat16": jnp.bfloat16,
}


def compute_dtype(name: str):
    """'float32' | 'bfloat16' -> jnp dtype (the activation/matmul dtype)."""
    try:
        return _COMPUTE_DTYPES[name]
    except KeyError:
        raise ValueError(
            f"unknown compute dtype {name!r}; expected one of "
            f"{sorted(_COMPUTE_DTYPES)}") from None


def validate_compute_dtype(name: str) -> str:
    """Raise early (config construction time) on an unknown dtype name."""
    compute_dtype(name)
    return name


@dataclasses.dataclass(frozen=True)
class DTypePolicy:
    """Resolved three-dtype policy threaded through the model stack.

    ``compute`` is the only axis that varies today; ``param`` and
    ``output`` are pinned float32 (no loss scaling needed on TPU — bf16
    keeps float32's exponent range, and every reduction that could lose
    mantissa accumulates in :data:`STATS_DTYPE`)."""

    compute_name: str = "float32"

    @property
    def compute(self):
        return compute_dtype(self.compute_name)

    @property
    def param(self):
        return PARAM_DTYPE

    @property
    def output(self):
        return OUTPUT_DTYPE

    @property
    def stats(self):
        return STATS_DTYPE

    def cast_compute(self, x):
        """Cast an activation into the compute dtype (no-op under f32)."""
        return x.astype(self.compute)

    def cast_output(self, x):
        """Cast an outward-facing tensor (logits) to the output dtype."""
        return x.astype(self.output)


def policy_for(compute_name: str = "float32") -> DTypePolicy:
    """The policy for a config-level compute-dtype string."""
    validate_compute_dtype(compute_name)
    return DTypePolicy(compute_name=compute_name)
