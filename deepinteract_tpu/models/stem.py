"""Factorized interaction stem: the decoders' first layer without the 2C
pair tensor.

The interaction tensor (``models/interaction.py``) has algebraic
structure: its value at ``(i, j)`` is the concatenation ``[f1_i | f2_j]``
— constant along columns in its first C channels and along rows in the
rest. Any *linear* map over it therefore splits exactly into a per-chain
part: for the dilated decoder's 1x1 entry conv,

    conv1x1([f1_i | f2_j]) = W1 @ f1_i + W2 @ f2_j + b,

so the first decoder layer is two O(L*C^2) per-chain matmuls plus a
broadcast add that materializes only ``num_channels`` (128) channels —
never the ``2C`` (256) input tensor. For DeepLab's 7x7/2 stem conv the
same split holds per channel block, and because the masked input is
separable (``x[i,j] = g1_i * m2_j  (+)  g2_j * m1_i`` with
``g = f * m``), each block reduces to a 1-D conv over its chain plus a
rank-K combine against shifted mask slices — exact up to float
association, including the zero-padding boundary (see
:func:`factorized_stem_conv`).

At the L=512 bucket the materialized float32 tensor is ~256 MB of
activations per sample before the first conv runs; the factorized stem
replaces it with the first layer's own output (half the channels, or a
quarter of the bytes under bf16) — verified by the fast-tier
``memory_analysis()`` regression test (tests/test_stem.py).

Both decoders accept either form: a materialized ``[B, L1, L2, 2C]``
tensor (kept for parity testing and checkpoint-import equivalence) or a
:class:`PairFactors` bundle of per-chain features/masks. The parameter
trees are IDENTICAL between the two paths — ``PairStem1x1`` declares the
same ``kernel``/``bias`` leaves as the ``nn.Conv`` it replaces, and the
DeepLab stem keeps its ``ConvNormAct_0/Conv_0`` naming — so checkpoints
(including torch imports, training/import_torch.py) are interchangeable.
"""

from __future__ import annotations

from typing import Any, Optional

import jax
import jax.numpy as jnp
from flax import linen as nn

from deepinteract_tpu.models.policy import FLOAT32

STEM_CHOICES = ("factorized", "materialized")


def validate_stem(name: str) -> str:
    if name not in STEM_CHOICES:
        raise ValueError(
            f"unknown interaction stem {name!r}; expected one of "
            f"{STEM_CHOICES}")
    return name


class PairFactors:
    """Per-chain factors of the interaction tensor: what the factorized
    stem consumes instead of the materialized ``[B, L1, L2, 2C]`` map.

    ``feats1``/``feats2`` are the encoded ``[B, L1, C]``/``[B, L2, C]``
    chain features, ``mask1``/``mask2`` the ``[B, L]`` validity masks
    (None = fully valid). ``shard_pair`` asks the stem to annotate its
    broadcast output for the mesh's 'pair' axis — the factorized
    equivalent of the sharding constraint the model used to place on the
    materialized tensor. Registered as a pytree (masks/features are
    children, ``shard_pair`` static) so factors cross jit/scan boundaries.
    """

    def __init__(self, feats1, feats2, mask1=None, mask2=None,
                 shard_pair: bool = False):
        self.feats1 = feats1
        self.feats2 = feats2
        self.mask1 = mask1
        self.mask2 = mask2
        self.shard_pair = bool(shard_pair)

    def pair_mask(self):
        """[B, L1, L2] validity mask, or None when both chains are fully
        valid."""
        if self.mask1 is None or self.mask2 is None:
            return None
        return self.mask1[:, :, None] & self.mask2[:, None, :]

    def tree_flatten(self):
        return ((self.feats1, self.feats2, self.mask1, self.mask2),
                self.shard_pair)

    @classmethod
    def tree_unflatten(cls, aux, children):
        return cls(*children, shard_pair=aux)


jax.tree_util.register_pytree_node(
    PairFactors,
    lambda pf: pf.tree_flatten(),
    PairFactors.tree_unflatten,
)


def pair_row_spec():
    """The row-dim PartitionSpec of a [B, L1, ...] pair-map tensor over
    the mesh's 'pair' axis — the ONE place it is spelled out. Everything
    that places or constrains pair rows (:func:`shard_pair_rows`,
    :func:`pair_row_sharding` for the serving engine's AOT
    ``in_shardings``) derives from here, so interior constraints and
    entry placements can never disagree. The batch dim stays
    unconstrained (its data-axis sharding flows from the inputs; pinning
    it would break batch-1 init traces)."""
    from jax.sharding import PartitionSpec as P

    from deepinteract_tpu.parallel.mesh import PAIR_AXIS

    return P(None, PAIR_AXIS)


def pair_row_sharding(mesh):
    """:func:`pair_row_spec` as a concrete ``NamedSharding`` — what the
    serving engine bakes into a pair-placement executable's
    ``in_shardings`` so per-chain factors arrive row-sharded instead of
    being resharded on entry."""
    from jax.sharding import NamedSharding

    return NamedSharding(mesh, pair_row_spec())


def shard_pair_rows(x):
    """with_sharding_constraint over the mesh's 'pair' axis on the row
    dim of a [B, L1, ...] pair-map tensor (requires an active mesh). The
    spec comes from :func:`pair_row_spec`; model.py and tiled.py
    annotate through this helper too."""
    return jax.lax.with_sharding_constraint(x, pair_row_spec())


class PairStem1x1(nn.Module):
    """The dilated decoder's entry 1x1 conv, computable from factors.

    Param tree is byte-identical to ``nn.Conv(features, (1, 1))`` (kernel
    ``[1, 1, 2C, F]`` lecun-normal + bias ``[F]`` zeros) so checkpoints —
    including torch imports mapping ``conv2d_1`` — load into either
    stem. Materialized inputs take the real conv; ``PairFactors`` split
    the kernel into its chain-1/chain-2 halves and materialize only the
    ``features``-channel output:

        out[b, i, j] = f1[b, i] @ K[:C] + f2[b, j] @ K[C:] + bias
    """

    features: int
    dtype: Any = FLOAT32

    @nn.compact
    def __call__(self, x):
        factored = isinstance(x, PairFactors)
        if factored:
            in_ch = x.feats1.shape[-1] + x.feats2.shape[-1]
        else:
            in_ch = x.shape[-1]
        kernel = self.param(
            "kernel", nn.initializers.lecun_normal(),
            (1, 1, in_ch, self.features))
        bias = self.param("bias", nn.initializers.zeros, (self.features,))
        k = kernel.astype(self.dtype)
        b = bias.astype(self.dtype)
        if not factored:
            return jax.lax.conv_general_dilated(
                x.astype(self.dtype), k, (1, 1), "VALID",
                dimension_numbers=("NHWC", "HWIO", "NHWC")) + b
        c1 = x.feats1.shape[-1]
        r1 = x.feats1.astype(self.dtype) @ k[0, 0, :c1]    # [B, L1, F]
        r2 = x.feats2.astype(self.dtype) @ k[0, 0, c1:] + b  # [B, L2, F]
        out = r1[:, :, None, :] + r2[:, None, :, :]
        # di: allow[jit-host-sync] shard_pair is pytree aux_data — a static bool at trace time
        if x.shard_pair:
            out = shard_pair_rows(out)
        return out


def _same_pad(size: int, kernel: int, stride: int):
    """Flax/XLA 'SAME' padding (lo, hi) for one spatial dim."""
    out = -(-size // stride)
    total = max((out - 1) * stride + kernel - size, 0)
    lo = total // 2
    return lo, total - lo, out


def _conv1d(x, kernel, stride: int, pad):
    """[B, L, Cin] x [K, Cin, Cout] -> [B, Lout, Cout]."""
    return jax.lax.conv_general_dilated(
        x, kernel, (stride,), (pad,),
        dimension_numbers=("NHC", "HIO", "NHC"))


def _shifted_mask(mask, kernel: int, stride: int, pad, out: int):
    """[B, L] 0-padded mask -> [B, Lout, K] with entry (o, t) =
    mask[stride*o + t - lo] (zero outside) — the per-tap mask slices the
    factorized combine contracts against."""
    lo, hi = pad
    mp = jnp.pad(mask, ((0, 0), (lo, hi)))
    cols = [mp[:, t : t + stride * (out - 1) + 1 : stride]
            for t in range(kernel)]
    return jnp.stack(cols, axis=-1)


def factorized_stem_conv(factors: PairFactors, kernel, stride: int,
                         dtype=None):
    """A KxK/stride 'SAME' conv of the *masked* materialized pair tensor,
    computed from per-chain factors without materializing it.

    ``kernel``: [K, K, C1+C2, F] (no bias — DeepLab's stem conv is
    bias-free). The masked tensor is channel-block separable,
    ``x[:, i, j, :C1] = g1[i] * m2[j]`` and
    ``x[:, i, j, C1:] = g2[j] * m1[i]`` with ``g = f * m``, so each
    block's conv is a 1-D conv over its own chain (taps x input channels
    folded into ``K * F`` output channels) contracted against the other
    chain's shifted-mask slices:

        y1[b,oi,oj,f] = sum_t A1[b,oi,t,f] * M2[b,oj,t]
        A1 = conv1d(g1, K1),  M2[b,oj,t] = m2_padded[b, stride*oj + t]

    (symmetrically for the second block) — exact vs the 2-D conv up to
    float association, including the zero-padded boundary, because zero
    padding extends masks and features by zeros consistently.

    Returns [B, Hout, Wout, F].
    """
    kh, kw, _, f = kernel.shape
    f1, f2 = factors.feats1, factors.feats2
    c1 = f1.shape[-1]
    dt = dtype or f1.dtype
    h, w = f1.shape[1], f2.shape[1]
    lo_h, hi_h, out_h = _same_pad(h, kh, stride)
    lo_w, hi_w, out_w = _same_pad(w, kw, stride)

    m1, m2 = factors.mask1, factors.mask2
    m1f = jnp.ones((f1.shape[0], h), dt) if m1 is None else m1.astype(dt)
    m2f = jnp.ones((f2.shape[0], w), dt) if m2 is None else m2.astype(dt)
    g1 = f1.astype(dt) * m1f[..., None]
    g2 = f2.astype(dt) * m2f[..., None]
    k = kernel.astype(dt)

    # Chain-1 block: conv over rows with output channels (col-tap, F).
    k1 = k[:, :, :c1, :].transpose(0, 2, 1, 3).reshape(kh, c1, kw * f)
    a1 = _conv1d(g1, k1, stride, (lo_h, hi_h)).reshape(-1, out_h, kw, f)
    m2s = _shifted_mask(m2f, kw, stride, (lo_w, hi_w), out_w)
    y = jnp.einsum("bitf,bjt->bijf", a1, m2s)

    # Chain-2 block: conv over columns with output channels (row-tap, F).
    c2 = k.shape[2] - c1
    k2 = k[:, :, c1:, :].transpose(1, 2, 0, 3).reshape(kw, c2, kh * f)
    a2 = _conv1d(g2, k2, stride, (lo_w, hi_w)).reshape(-1, out_w, kh, f)
    m1s = _shifted_mask(m1f, kh, stride, (lo_h, hi_h), out_h)
    y = y + jnp.einsum("bjtf,bit->bijf", a2, m1s)
    # di: allow[jit-host-sync] shard_pair is pytree aux_data — a static bool at trace time
    if factors.shard_pair:
        y = shard_pair_rows(y)
    return y


class DeepLabStemConv(nn.Module):
    """DeepLab's 7x7/2 bias-free stem conv, computable from factors.

    Declares the exact ``kernel`` leaf ``nn.Conv(features, (7, 7),
    use_bias=False)`` would — instantiated under the encoder's historical
    ``ConvNormAct_0/Conv_0`` scope so the DeepLab param tree is unchanged
    and both stem modes share checkpoints."""

    features: int
    kernel_size: int = 7
    stride: int = 2
    dtype: Any = FLOAT32

    @nn.compact
    def __call__(self, x):
        ks = self.kernel_size
        factored = isinstance(x, PairFactors)
        in_ch = (x.feats1.shape[-1] + x.feats2.shape[-1]
                 if factored else x.shape[-1])
        kernel = self.param(
            "kernel", nn.initializers.lecun_normal(),
            (ks, ks, in_ch, self.features))
        if factored:
            return factorized_stem_conv(x, kernel, self.stride,
                                        dtype=self.dtype)
        h, w = x.shape[1], x.shape[2]
        lo_h, hi_h, _ = _same_pad(h, ks, self.stride)
        lo_w, hi_w, _ = _same_pad(w, ks, self.stride)
        return jax.lax.conv_general_dilated(
            x.astype(self.dtype), kernel.astype(self.dtype),
            (self.stride, self.stride),
            ((lo_h, hi_h), (lo_w, hi_w)),
            dimension_numbers=("NHWC", "HWIO", "NHWC"))


def materialized_interaction_bytes(batch: int, l1: int, l2: int,
                                   channels_2c: int,
                                   dtype_bytes: int = 4) -> int:
    """Bytes the materialized ``[B, L1, L2, 2C]`` tensor would occupy —
    the bench's 'materialized-equivalent' reference for
    ``interaction_bytes`` bucket records."""
    return batch * l1 * l2 * channels_2c * dtype_bytes
