"""Geometric Transformer (GT) — flax implementation on dense [N, K] graphs.

Reimplements the reference model family
(``project/utils/deepinteract_modules.py``):
  * InitEdgeModule            (:128-264)  — gated edge initializer
  * ConformationModule        (:267-452)  — edge-neighborhood geometry module
  * MultiHeadGeometricAttention (:34-121) — via :mod:`deepinteract_tpu.ops`
  * GeometricTransformerLayer (:500-732)  — node+edge updating layer
  * FinalGTLayer              (:735-951)  — node-only final layer
  * GeometricTransformer      (:1255-1466) — init-edge + (L-1) layers + final

Design notes (TPU-first, not a port):
  * All edge state lives in ``[B, N, K, C]`` tensors; every reference
    ``apply_edges`` UDF becomes dense elementwise algebra, every
    neighbor-edge gather a ``take`` over flat edge ids.
  * The reference's O(N^2) ``i_all`` node-index trick
    (``deepinteract_modules.py:258-264``) only ever materializes node indices
    0..N-1; it is replaced by a direct index embedding (same math, O(N)).
  * ``disable_geometric_mode`` degrades the conformation module to a single
    Linear over raw edge features — the plain Graph Transformer ablation.
"""

from __future__ import annotations

import dataclasses
import functools

import jax.numpy as jnp
from flax import linen as nn

from deepinteract_tpu.models import policy

from deepinteract_tpu import constants as C
from deepinteract_tpu.data.graph import ProteinGraph
from deepinteract_tpu.models.layers import (
    GODense,
    FeatureNorm,
    MLP,
    ResBlock,
    glorot_orthogonal,
    uniform_sqrt3,
)
from deepinteract_tpu.ops.attention import edge_attention


@dataclasses.dataclass(frozen=True)
class GTConfig:
    """Hyperparameters (defaults = reference defaults,
    deepinteract_modules.py:1262-1283 and LitGINI args :1484-1489)."""

    num_layers: int = 2
    hidden: int = 128
    num_heads: int = 4
    shared_embed: int = 64
    dist_embed: int = 8
    dir_embed: int = 8
    orient_embed: int = 8
    amide_embed: int = 8
    num_pre_res_blocks: int = 2
    num_post_res_blocks: int = 2
    norm_type: str = "batch"  # 'batch' | 'layer'
    dropout_rate: float = 0.2
    residual: bool = True
    node_count_limit: int = C.NODE_COUNT_LIMIT
    disable_geometric_mode: bool = False
    # 'scatter' reproduces the reference's DGL edge softmax exactly
    # (normalize over each node's *incoming* edges, deepinteract_modules.py:
    # 91-116); 'gather' normalizes over the K out-edges — a transposed-graph
    # attention that only coincides on symmetric kNN graphs. Default is the
    # reference-exact mode; see tests/test_attention_modes.py for the
    # measured divergence on realistic asymmetric kNN graphs.
    attention_mode: str = "scatter"  # 'scatter' (reference-exact) | 'gather' (TPU-fast)
    # 'auto': use the Pallas fused kernel (ops/pallas_attention.py) on TPU
    # for scatter mode wherever (a) the gen-2 kernel supports the
    # (bucket, dtype) shape and (b) the measured A/B evidence store
    # (DI_ATTENTION_AB, written by tools/scan_ab.py / bench's inline A/B)
    # does not record the kernel LOSING for the bucket — the autotune
    # guard that keeps a BENCH_r05-style 0.97x regression from shipping
    # as the default (resolve_attention_impl). jnp elsewhere.
    # 'jnp'/'pallas' force one path ('pallas' still falls back on
    # unsupported buckets).
    attention_impl: str = "auto"
    # Edge-block grid sizes of the Pallas kernel (forward / backward);
    # None = the kernel's built-in per-bucket heuristic. Real tunable
    # parameters (ops/pallas_attention.py:edge_block_options) searched by
    # the autotuner (tuning/space.py) and adopted from its store.
    pallas_fwd_blocks: "int | None" = None
    pallas_bwd_blocks: "int | None" = None
    # Activation/matmul compute dtype for the whole encoder stack
    # ('float32' | 'bfloat16') — one leg of the model-wide dtype policy
    # (models/policy.py). Params, normalization statistics, and softmax
    # accumulators stay float32; bf16 halves the edge-tensor HBM traffic.
    compute_dtype: str = "float32"

    @property
    def dtype(self):
        return policy.compute_dtype(self.compute_dtype)


def _split_geo_feats(orig_edge_feats: jnp.ndarray):
    """Slice raw 28-d edge features into (dist, dir, orient, amide) groups
    (reference ``get_geo_feats_from_edges``, deepinteract_utils.py:70-76)."""
    return (
        orig_edge_feats[..., C.EDGE_DIST_FEATS],
        orig_edge_feats[..., C.EDGE_DIR_FEATS],
        orig_edge_feats[..., C.EDGE_ORIENT_FEATS],
        orig_edge_feats[..., C.EDGE_AMIDE_ANGLE, None],
    )


def _edge_messages(orig_edge_feats: jnp.ndarray):
    """[pos_enc, weight] channels (reference edge_messages_init,
    deepinteract_modules.py:227-231)."""
    return jnp.stack(
        [orig_edge_feats[..., C.EDGE_POS_ENC], orig_edge_feats[..., C.EDGE_WEIGHT]], axis=-1
    )


class InitEdgeModule(nn.Module):
    """Gated edge initializer (deepinteract_modules.py:128-264)."""

    cfg: GTConfig

    @nn.compact
    def __call__(self, graph: ProteinGraph, orig_edge_feats: jnp.ndarray) -> jnp.ndarray:
        cfg = self.cfg
        ch = cfg.hidden
        GODense_ = functools.partial(GODense, dtype=cfg.dtype)
        b, n, k = graph.nbr_idx.shape

        if n > cfg.node_count_limit:
            raise ValueError(
                f"padded node count {n} exceeds node_count_limit="
                f"{cfg.node_count_limit}; raise GTConfig.node_count_limit for "
                "long-context buckets (jnp.take would silently clamp indices)"
            )
        node_embedding = nn.Embed(
            cfg.node_count_limit, ch, embedding_init=uniform_sqrt3(),
            dtype=cfg.dtype, name="node_embedding"
        )
        node_ids = jnp.broadcast_to(jnp.arange(n, dtype=jnp.int32), (b, n))
        node_emb = node_embedding(node_ids)  # [B, N, C]
        src_emb = jnp.broadcast_to(node_emb[:, :, None, :], (b, n, k, ch))
        dst_emb = node_emb[jnp.arange(b)[:, None, None], graph.nbr_idx]  # [B,N,K,C]

        msgs = _edge_messages(orig_edge_feats)
        dist, direc, orient, amide = _split_geo_feats(orig_edge_feats)

        msg0 = GODense_(ch, use_bias=False, name="edge_messages_linear_0")(msgs)
        dist0 = nn.silu(GODense_(ch, use_bias=False, name="dist_linear_0")(dist))
        dir0 = nn.silu(GODense_(ch, use_bias=False, name="dir_linear_0")(direc))
        orient0 = nn.silu(GODense_(ch, use_bias=False, name="orient_linear_0")(orient))
        amide0 = nn.silu(GODense_(ch, use_bias=False, name="amide_linear_0")(amide))

        combined = nn.silu(
            GODense_(ch, use_bias=False, name="combined_linear_0")(
                jnp.concatenate([src_emb, dst_emb, msg0, dist0, dir0, orient0, amide0], axis=-1)
            )
        )

        # Gated second branch; note the message branch is NOT activated
        # (reference edge_messages_1, deepinteract_modules.py:240-246).
        msg1 = GODense_(ch, use_bias=False, name="edge_messages_linear_1")(msgs) * combined
        dist1 = nn.silu(GODense_(ch, use_bias=False, name="dist_linear_1")(dist)) * combined
        dir1 = nn.silu(GODense_(ch, use_bias=False, name="dir_linear_1")(direc)) * combined
        orient1 = nn.silu(GODense_(ch, use_bias=False, name="orient_linear_1")(orient)) * combined
        amide1 = nn.silu(GODense_(ch, use_bias=False, name="amide_linear_1")(amide)) * combined

        combined_out = C.NUM_EDGE_MESSAGE_FEATS + C.NUM_DIST_FEATS + C.NUM_DIR_FEATS \
            + C.NUM_ORIENT_FEATS + C.NUM_AMIDE_FEATS
        out = GODense_(combined_out, use_bias=False, name="combined_linear_1")(
            msg1 + dist1 + dir1 + orient1 + amide1
        )
        return GODense_(ch, use_bias=False, name="combined_linear_2")(out)


class ConformationModule(nn.Module):
    """Edge-neighborhood geometry module (deepinteract_modules.py:267-452)."""

    cfg: GTConfig

    @nn.compact
    def __call__(
        self,
        graph: ProteinGraph,
        edge_feats: jnp.ndarray,
        orig_edge_feats: jnp.ndarray,
        train: bool = False,
    ) -> jnp.ndarray:
        cfg = self.cfg
        ch = cfg.hidden
        GODense_ = functools.partial(GODense, dtype=cfg.dtype)
        b, n, k = graph.nbr_idx.shape
        edge_mask = graph.edge_mask()

        dist, direc, orient, amide = _split_geo_feats(orig_edge_feats)

        # Gather sampled neighboring-edge features by flat edge id, stacking
        # src-side and dst-side neighborhoods (reference cat at :387-389).
        flat = edge_feats.reshape(b, n * k, ch)
        batch_ix = jnp.arange(b)[:, None, None, None]
        src_nbr = flat[batch_ix, graph.src_nbr_eids]  # [B,N,K,G,C]
        dst_nbr = flat[batch_ix, graph.dst_nbr_eids]
        nbr = jnp.concatenate([src_nbr, dst_nbr], axis=3)  # [B,N,K,2G,C]

        nbr = nn.silu(GODense_(ch, name="nbr_linear")(nbr))
        res_edge_feats = edge_feats

        emb_dist = GODense_(ch, use_bias=False, name="dist_linear_1")(
            GODense_(cfg.dist_embed, use_bias=False, name="dist_linear_0")(dist)
        )
        nbr = nbr * emb_dist[..., None, :]
        nbr = nn.silu(GODense_(cfg.shared_embed, use_bias=False, name="downward_proj")(nbr))
        nbr = nbr * GODense_(cfg.shared_embed, use_bias=False, name="dir_linear_1")(
            GODense_(cfg.dir_embed, use_bias=False, name="dir_linear_0")(direc)
        )[..., None, :]
        nbr = nbr * GODense_(cfg.shared_embed, use_bias=False, name="orient_linear_1")(
            GODense_(cfg.orient_embed, use_bias=False, name="orient_linear_0")(orient)
        )[..., None, :]
        nbr = nbr * GODense_(cfg.shared_embed, use_bias=False, name="amide_linear_1")(
            GODense_(cfg.amide_embed, use_bias=False, name="amide_linear_0")(amide)
        )[..., None, :]
        nbr = jnp.sum(nbr, axis=3)  # aggregate the 2G neighborhood
        nbr = nn.silu(GODense_(ch, use_bias=False, name="upward_proj")(nbr))

        out = GODense_(ch, name="orig_msg_linear")(res_edge_feats) + nbr

        for i in range(cfg.num_pre_res_blocks):
            out = ResBlock(ch, cfg.norm_type, dtype=cfg.dtype,
                           name=f"pre_res_block_{i}")(out, edge_mask, train)
        out = res_edge_feats + nn.silu(GODense_(ch, name="res_connect_linear")(out))
        for i in range(cfg.num_post_res_blocks):
            out = ResBlock(ch, cfg.norm_type, dtype=cfg.dtype,
                           name=f"post_res_block_{i}")(out, edge_mask, train)

        gated = (
            GODense_(ch, use_bias=False, name="final_dist_linear")(dist) * out
            + GODense_(ch, use_bias=False, name="final_dir_linear")(direc) * out
            + GODense_(ch, use_bias=False, name="final_orient_linear")(orient) * out
            + GODense_(ch, use_bias=False, name="final_amide_linear")(amide) * out
        )
        return res_edge_feats + nn.silu(GODense_(ch, name="final_linear")(gated))


class PlainEdgeModule(nn.Module):
    """``disable_geometric_mode`` conformation stand-in: one Linear over
    [edge messages | raw edge feats] (deepinteract_modules.py:898-905)."""

    cfg: GTConfig

    @nn.compact
    def __call__(self, orig_edge_feats: jnp.ndarray) -> jnp.ndarray:
        x = jnp.concatenate([_edge_messages(orig_edge_feats), orig_edge_feats], axis=-1)
        return GODense(self.cfg.hidden, use_bias=False, dtype=self.cfg.dtype,
                       name="linear")(x)


def _dispatch_attention(cfg: "GTConfig", q, kk, v, proj_e, nbr_idx, edge_mask,
                        train: bool = False):
    """Pick the attention implementation: Pallas fused kernel on TPU for
    reference-exact scatter mode on supported buckets, jnp otherwise.

    ``auto`` routing is evidence-driven (VERDICT r4 item 7) and, since
    gen-2, autotune-GUARDED: the decision lives in
    ``ops.pallas_attention.resolve_attention_impl`` — TPU backend +
    :func:`~deepinteract_tpu.ops.pallas_attention.supports` (now
    dtype-aware: the live q.dtype threads through so bf16 buckets get
    the halved working-set legality) + the measured A/B evidence store
    (``DI_ATTENTION_AB``, written by ``tools/scan_ab.py`` and bench's
    inline A/B). A bucket where the kernel measurably LOSES vs jnp
    (BENCH_r05: 0.97x forward at b1 p128) routes to jnp with the reason
    logged — the kernel can win its way back only through fresh
    evidence. Force with attention_impl='pallas'/'jnp' (the bench's A/B
    does exactly that). ``train`` is accepted for signature stability
    (routing no longer depends on it)."""
    del train  # routing is shape/backend/evidence-driven (see docstring)
    import jax

    from deepinteract_tpu.ops.pallas_attention import resolve_attention_impl

    n = q.shape[1]
    impl, _reason = resolve_attention_impl(
        cfg.attention_mode, cfg.attention_impl, n,
        batch=q.shape[0], knn=nbr_idx.shape[-1],
        hidden=q.shape[-2] * q.shape[-1], num_heads=q.shape[-2],
        dtype=q.dtype, backend=jax.default_backend())
    if impl == "pallas":
        from deepinteract_tpu.ops.pallas_attention import edge_attention_pallas

        # Off-TPU (forced 'pallas', e.g. CPU tests) runs the interpreter.
        interpret = jax.default_backend() != "tpu"
        return edge_attention_pallas(q, kk, v, proj_e, nbr_idx, edge_mask,
                                     interpret, cfg.pallas_fwd_blocks,
                                     cfg.pallas_bwd_blocks)
    return edge_attention(q, kk, v, proj_e, nbr_idx, edge_mask, mode=cfg.attention_mode)


class MultiHeadGeometricAttention(nn.Module):
    """Q/K/V + edge projections feeding the fused edge-attention op
    (deepinteract_modules.py:34-121)."""

    cfg: GTConfig
    update_edge_feats: bool = True

    @nn.compact
    def __call__(self, graph: ProteinGraph, node_feats, edge_feats,
                 train: bool = False):
        cfg = self.cfg
        dt = cfg.dtype
        h, d = cfg.num_heads, cfg.hidden // cfg.num_heads
        b, n, k = graph.nbr_idx.shape
        # Bias only if a Linear changes sizes (it never does here) —
        # reference deepinteract_modules.py:617-623.
        q = GODense(cfg.hidden, use_bias=False, dtype=dt, name="Q")(node_feats).reshape(b, n, h, d)
        kk = GODense(cfg.hidden, use_bias=False, dtype=dt, name="K")(node_feats).reshape(b, n, h, d)
        v = GODense(cfg.hidden, use_bias=False, dtype=dt, name="V")(node_feats).reshape(b, n, h, d)
        proj_e = GODense(cfg.hidden, use_bias=False, dtype=dt,
                         name="edge_feats_projection")(
            edge_feats
        ).reshape(b, n, k, h, d)

        h_out, e_out = _dispatch_attention(
            cfg, q, kk, v, proj_e, graph.nbr_idx, graph.edge_mask(), train
        )
        # Both impls may return float32 accumulators (the Pallas kernel
        # always does); the policy keeps activations in the compute dtype.
        h_out = h_out.astype(dt).reshape(b, n, cfg.hidden)
        e_out = (e_out.astype(dt).reshape(b, n, k, cfg.hidden)
                 if self.update_edge_feats else None)
        return h_out, e_out


class GeometricTransformerLayer(nn.Module):
    """One GT layer: conformation -> norm -> MHA -> O-proj -> residual ->
    norm -> FFN -> residual, updating nodes and (optionally) edges
    (run_gt_layer, deepinteract_modules.py:669-727; final-layer variant
    :894-946)."""

    cfg: GTConfig
    update_edge_feats: bool = True

    @nn.compact
    def __call__(self, graph, node_feats, edge_feats, orig_edge_feats, train: bool = False):
        cfg = self.cfg
        node_mask, edge_mask = graph.node_mask, graph.edge_mask()
        node_in1, edge_in1 = node_feats, edge_feats

        if cfg.disable_geometric_mode:
            edge_feats = PlainEdgeModule(cfg, name="conformation_module")(orig_edge_feats)
        else:
            edge_feats = ConformationModule(cfg, name="conformation_module")(
                graph, edge_feats, orig_edge_feats, train
            )

        node_feats = FeatureNorm(cfg.norm_type, dtype=cfg.dtype,
                                 name="norm1_node")(node_feats, node_mask, train)
        edge_feats = FeatureNorm(cfg.norm_type, dtype=cfg.dtype,
                                 name="norm1_edge")(edge_feats, edge_mask, train)

        node_attn, edge_attn = MultiHeadGeometricAttention(
            cfg, update_edge_feats=self.update_edge_feats, name="mha"
        )(graph, node_feats, edge_feats, train)

        drop = nn.Dropout(cfg.dropout_rate, deterministic=not train)
        node_feats = GODense(cfg.hidden, dtype=cfg.dtype, name="O_node")(drop(node_attn))
        if cfg.residual:
            node_feats = node_in1 + node_feats
        node_in2 = node_feats
        node_feats = FeatureNorm(cfg.norm_type, dtype=cfg.dtype,
                                 name="norm2_node")(node_feats, node_mask, train)
        node_feats = MLP(cfg.hidden, cfg.dropout_rate, dtype=cfg.dtype,
                         name="node_mlp")(node_feats, train)
        if cfg.residual:
            node_feats = node_in2 + node_feats

        if not self.update_edge_feats:
            return node_feats, None

        edge_feats = GODense(cfg.hidden, dtype=cfg.dtype, name="O_edge")(drop(edge_attn))
        if cfg.residual:
            edge_feats = edge_in1 + edge_feats
        edge_in2 = edge_feats
        edge_feats = FeatureNorm(cfg.norm_type, dtype=cfg.dtype,
                                 name="norm2_edge")(edge_feats, edge_mask, train)
        edge_feats = MLP(cfg.hidden, cfg.dropout_rate, dtype=cfg.dtype,
                         name="edge_mlp")(edge_feats, train)
        if cfg.residual:
            edge_feats = edge_in2 + edge_feats
        return node_feats, edge_feats


class GeometricTransformer(nn.Module):
    """Full GT stack (DGLGeometricTransformer, deepinteract_modules.py:1255-
    1466): edge init + (num_layers - 1) node+edge layers + 1 node-only final
    layer. Expects node_feats already embedded to ``hidden`` channels."""

    cfg: GTConfig

    @nn.compact
    def __call__(self, graph: ProteinGraph, node_feats: jnp.ndarray, train: bool = False):
        cfg = self.cfg
        # Entry cast into the compute dtype (no-op under float32): the raw
        # feature tensors arrive float32 from the loader.
        node_feats = node_feats.astype(cfg.dtype)
        orig_edge_feats = graph.edge_feats.astype(cfg.dtype)  # raw 28-d

        if cfg.disable_geometric_mode:
            edge_feats = PlainEdgeModule(cfg, name="init_edge_module")(orig_edge_feats)
        else:
            edge_feats = InitEdgeModule(cfg, name="init_edge_module")(graph, orig_edge_feats)

        for i in range(max(0, cfg.num_layers - 1)):
            node_feats, edge_feats = GeometricTransformerLayer(
                cfg, update_edge_feats=True, name=f"gt_layer_{i}"
            )(graph, node_feats, edge_feats, orig_edge_feats, train)

        if cfg.num_layers > 0:
            node_feats, _ = GeometricTransformerLayer(
                cfg, update_edge_feats=False, name="final_gt_layer"
            )(graph, node_feats, edge_feats, orig_edge_feats, train)

        node_feats = node_feats * graph.node_mask[..., None].astype(cfg.dtype)
        return node_feats, edge_feats
