"""Dilated squeeze-excitation ResNet interaction decoder (NHWC, XLA convs).

Reimplements the reference decoder stack
(``project/utils/deepinteract_modules.py:954-1248``):
  * SEBlock                     (:954-970)
  * ResNet (dilated bottleneck) (:973-1106)
  * MultiHeadRegionalAttention  (:1109-1152)
  * ResNet2DInputWithOptAttention (:1155-1248)

TPU-first changes: NHWC layout (TPU conv native), instance norm implemented
with pair-map masking so padded rows/cols do not pollute statistics, and the
whole stack is shape-static so XLA fuses the 1x1 convs into the dilated 3x3s.
The final positive-class bias is initialized to -7 so positives start at
p ~= 0.001 (reference :1224-1226).
"""

from __future__ import annotations

import dataclasses
from typing import Any, Optional, Sequence

import jax
import jax.numpy as jnp
from flax import linen as nn

from deepinteract_tpu.models import policy
from deepinteract_tpu.models.policy import FLOAT32, OUTPUT_DTYPE, STATS_DTYPE
from deepinteract_tpu.models.stem import PairFactors, PairStem1x1


@dataclasses.dataclass(frozen=True)
class DecoderConfig:
    """Defaults mirror the reference (deepinteract_modules.py:1157-1167,
    LitGINI num_interact_layers=14 -> num_chunks=14)."""

    num_chunks: int = 14
    in_channels: int = 256  # 2 * GNN hidden
    num_channels: int = 128
    num_classes: int = 2
    dilation_cycle: Sequence[int] = (1, 2, 4, 8)
    use_attention: bool = False
    num_attention_heads: int = 4
    dropout_rate: float = 0.2
    region_size: int = 3
    # Rematerialize each bottleneck block in backward (jax.checkpoint):
    # activations inside a block are recomputed instead of stored, cutting
    # train-step HBM by ~4x on the pair-map decoder (the batch-8 128-pad
    # train step OOMs a 16G v5e chip without it). No reference equivalent —
    # torch keeps all activations. Param tree is identical either way.
    remat: bool = False
    # Checkpoint policy under ``remat``: 'full' recomputes the whole block
    # in backward (max memory saving, ~one extra decoder forward of FLOPs);
    # 'convs' saves every conv output (tagged via
    # ``jax.ad_checkpoint.checkpoint_name``) and recomputes only the
    # elementwise chain between convs (norm affines, elu, SE gate, mask
    # multiplies) — the convs, which are ~all the FLOPs, are never
    # recomputed, at ~3x the residual memory of 'full' (3 conv outputs +
    # block input per block vs block input only). The backward's FLOP
    # count is then the no-remat 3x-forward figure. Ignored when ``remat``
    # is False. Measured (tools/remat_ab.py, v5e p128 bf16 scanned): the
    # trade is batch-dependent — at b8 'convs' is 0.89x of 'full' (the
    # backward is bandwidth-bound; the larger residual set's HBM traffic
    # outweighs the conv recompute it saves), at b4 'convs' wins 1.21x.
    # 'full' stays the default (b8 is the throughput flagship and full
    # wins there).
    remat_policy: str = "full"
    # Activation compute dtype for the conv stack ('float32' | 'bfloat16').
    # bfloat16 halves HBM traffic on the pair-map activations; params stay
    # float32 and instance-norm statistics are computed in float32
    # regardless (masked_instance_norm upcasts), so the param/checkpoint
    # tree is unchanged. Final logits are float32. Measured on a v5e at
    # 128-pad: neutral-to-slightly-slower (2.99 vs 2.82 ms/step scanned —
    # XLA already runs f32 convs through bf16 MXU passes, so only the
    # bandwidth saving is new, and 128x128 maps are not bandwidth-bound);
    # intended for larger pair maps / batch sizes.
    compute_dtype: str = "float32"
    # Roll the base ResNet's num_chunks identical dilation cycles into one
    # ``nn.scan`` over stacked per-chunk params instead of unrolling 56
    # blocks into the HLO. Semantics are identical (see
    # tests/test_decoder.py scan-parity); XLA traces/compiles ONE 4-block
    # cycle instead of 14, cutting train-step compile time ~5-8x (the r3
    # p256 train step took 245 s to compile, VERDICT r3 item 2). Param tree
    # changes: ``base_resnet/chunks/block_d{d}/...`` leaves gain a leading
    # [num_chunks] axis; ``stack_chunk_params``/``unstack_chunk_params``
    # convert to/from the unrolled layout and the torch importer handles
    # both. False restores the r3 unrolled tree byte-for-byte.
    scan_chunks: bool = True
    # De-padded statistics fast path (see BottleneckBlock.depad): computes
    # the SAME per-valid-pixel statistics with unmasked/closed-form sums
    # where the pad contribution is analytically known. Exact up to float
    # association; masked-reduction passes measured ~35% of decoder
    # forward time on a v5e. False restores the plain masked formulation.
    depad_stats: bool = True

    @property
    def dtype(self):
        return policy.compute_dtype(self.compute_dtype)


def _remat_transform(policy: str):
    """The ``nn.remat`` wrapper for a decoder remat policy ('full' |
    'convs' — see :class:`DecoderConfig.remat_policy`)."""
    if policy == "convs":
        return lambda mod: nn.remat(
            mod,
            policy=jax.checkpoint_policies.save_only_these_names(
                "decoder_conv"),
        )
    if policy != "full":
        raise ValueError(f"unknown remat_policy {policy!r}; "
                         "expected 'full' or 'convs'")
    return nn.remat


def _tag_conv(x, enabled: bool):
    """Mark a conv output as a saved residual for the 'convs' remat
    policy. Identity in math, but the name marker perturbs XLA's fusion
    choices (measured: scan-vs-sequential train steps drift past the 5e-5
    float32 re-association tolerance with markers present), so it is
    emitted ONLY when the convs policy actually consumes it — default
    graphs stay byte-identical to the unmarked form."""
    if not enabled:
        return x
    from jax.ad_checkpoint import checkpoint_name

    return checkpoint_name(x, "decoder_conv")


def masked_instance_norm(x: jnp.ndarray, mask: Optional[jnp.ndarray], scale, bias, eps=1e-6):
    """InstanceNorm2d over valid H, W positions per sample/channel.

    x: [B, H, W, C]; mask: [B, H, W] or None. Reference uses
    ``nn.InstanceNorm2d(eps=1e-06, affine=True)`` on unpadded maps; masking
    makes the padded formulation equivalent. Statistics are always computed
    in float32 (bf16 spatial sums lose too much precision); the result is
    cast back to the input dtype.

    Cost note (measured, tools/decoder_ablation.py): masked norms cost
    ~90 us each on a v5e while the unmasked path fuses to ~free. This is
    the FALLBACK formulation (depad_stats=False) — the default decoder
    uses :func:`depadded_instance_norm`, which eliminates the masked
    reductions entirely — so the masked branch keeps the numerically
    robust two-pass (x - mean)^2 variance (ADVICE r4 item 1).
    """
    in_dtype = x.dtype
    f32 = STATS_DTYPE
    if mask is None:
        n = x.shape[1] * x.shape[2]
        s1 = jnp.sum(x, axis=(1, 2), keepdims=True, dtype=f32)
        s2 = jnp.sum(jnp.square(x.astype(f32)), axis=(1, 2), keepdims=True)
        mean = s1 / n
        var = jnp.maximum(s2 / n - jnp.square(mean), 0.0)
    else:
        # Two-pass (x - mean)^2 variance (ADVICE r4 item 1): this is the
        # fallback path (depad_stats=False), so numerical robustness for
        # large-|mean| activations beats saving the second reduction.
        m = mask[..., None].astype(f32)
        xm = x.astype(f32) * m
        count = jnp.maximum(jnp.sum(m, axis=(1, 2), keepdims=True), 1.0)
        mean = jnp.sum(xm, axis=(1, 2), keepdims=True) / count
        var = jnp.sum(jnp.square((x.astype(f32) - mean)) * m,
                      axis=(1, 2), keepdims=True) / count
    y = (x.astype(f32) - mean) * jax.lax.rsqrt(var + eps) * scale + bias
    if mask is not None:
        y = y * mask[..., None]
    return y.astype(in_dtype)


def depadded_instance_norm(x, count, pad_value, scale, bias, eps=1e-6):
    """Exact masked instance norm WITHOUT masked reductions or a masked
    output — the pad-value-tracking formulation (r5).

    Valid when every padded pixel of ``x`` holds the same per-channel value
    ``pad_value`` ([B, 1, 1, C] in x's dtype, or None meaning zero): the
    pad contribution to the raw moments is then closed-form (n_pad * pv,
    n_pad * pv^2) and the sums run UNMASKED — which XLA fuses to near-free,
    while mask-broadcast reductions measured ~17-30 us each on a v5e
    (tools/decoder_ablation.py). Unlike the r4 version, the output is NOT
    re-masked; instead the value every padded pixel now holds — the same
    affine applied to ``pad_value`` — is returned alongside, so the caller
    keeps tracking it symbolically. Statistics match
    :func:`masked_instance_norm` up to float association; the decoder's
    padding-invariance tests are the oracle.

    count: [B, 1, 1, 1] float32 — number of valid pixels (precomputed once
    per decoder call and shared by every norm).

    Returns ``(y, pad_value_out)`` with ``pad_value_out`` [B, 1, 1, C] in
    x's dtype.
    """
    f32 = STATS_DTYPE
    in_dtype = x.dtype
    n_total = float(x.shape[1] * x.shape[2])
    s1 = jnp.sum(x, axis=(1, 2), keepdims=True, dtype=f32)
    s2 = jnp.sum(jnp.square(x.astype(f32)), axis=(1, 2), keepdims=True)
    if pad_value is not None:
        n_pad = n_total - count
        pv = pad_value.astype(f32)
        s1 = s1 - n_pad * pv
        s2 = s2 - n_pad * jnp.square(pv)
    mean = s1 / count
    # Single-pass var = E[x^2] - mu^2: post-conv activations are O(1)-mean
    # so cancellation is negligible next to eps (the depad-vs-masked
    # large-mean parity test bounds it); the plain masked path keeps the
    # two-pass form (ADVICE r4 item 1).
    var = jnp.maximum(s2 / count - jnp.square(mean), 0.0)
    rs = jax.lax.rsqrt(var + eps) * scale
    y = (x.astype(f32) - mean) * rs + bias
    pv_in = pad_value.astype(f32) if pad_value is not None else 0.0
    pv_out = (pv_in - mean) * rs + bias
    return y.astype(in_dtype), pv_out.astype(in_dtype)


class InstanceNorm(nn.Module):
    features: int

    @nn.compact
    def __call__(self, x, mask=None, count=None, pad_value=None,
                 depad: bool = False):
        scale = self.param("scale", nn.initializers.ones, (self.features,))
        bias = self.param("bias", nn.initializers.zeros, (self.features,))
        if depad and count is not None:
            return depadded_instance_norm(x, count, pad_value, scale, bias)
        return masked_instance_norm(x, mask, scale, bias)


class BiasConv1x1(nn.Module):
    """1x1 conv whose tracked pad value is its own bias — the r10
    replacement for the r5 pad-value matvec machinery (``PVConv1x1``).

    Contract: the caller guarantees every padded pixel of ``x`` is ZERO
    (the fast path fuses the zeroing multiply into the preceding elu, so
    it rides an elementwise pass that already exists). A 1x1 conv of a
    zero pixel is then exactly its bias, so the pad value out is the bias
    parameter broadcast to [1, 1, 1, O] — closed form, no data-dependent
    work. The r5 design instead tracked an arbitrary [B, 1, 1, C] pad
    value through a broadcast-multiply + sum of the conv kernel; those
    tiny contractions cost a ~24 us launch each on a v5e and the 112 of
    them per decoder forward were the top re-mask-class sink in the PR-7
    attribution census (`python -m deepinteract_tpu.cli.attribute
    --census decoder`) — this class deletes them outright.

    Param tree is identical to ``nn.Conv(features, (1, 1))`` (kernel
    [1, 1, I, O] lecun-normal, bias [O] zeros) — checkpoints are
    interchangeable."""

    features: int
    dtype: Any = FLOAT32

    @nn.compact
    def __call__(self, x):
        kernel = self.param(
            "kernel", nn.initializers.lecun_normal(),
            (1, 1, x.shape[-1], self.features))
        bias = self.param("bias", nn.initializers.zeros, (self.features,))
        k = kernel.astype(self.dtype)
        b = bias.astype(self.dtype)
        y = jax.lax.conv_general_dilated(
            x.astype(self.dtype), k, (1, 1), "VALID",
            dimension_numbers=("NHWC", "HWIO", "NHWC")) + b
        return y, b[None, None, None, :]


class SEBlock(nn.Module):
    """Squeeze-and-excitation over the (masked) spatial mean
    (deepinteract_modules.py:954-970).

    With ``count`` + ``pad_value`` (the de-padded fast path) the pooled
    mean runs unmasked with a closed-form pad correction and the call
    returns ``(y, pad_value_out)`` — the gate applied to the tracked pad
    value — instead of a masked tensor."""

    channels: int
    ratio: int = 16
    dtype: Any = FLOAT32

    @nn.compact
    def __call__(self, x, mask=None, count=None, pad_value=None):
        # f32-accumulated spatial mean (like the norms) without an
        # explicit f32 copy of the activation — see masked_instance_norm's
        # cost note.
        depad = count is not None and pad_value is not None
        if mask is None:
            pooled = jnp.sum(x, axis=(1, 2), dtype=STATS_DTYPE) / (
                x.shape[1] * x.shape[2])
        elif depad:
            n_pad = float(x.shape[1] * x.shape[2]) - count[:, 0, 0, :]
            s = jnp.sum(x, axis=(1, 2), dtype=STATS_DTYPE)
            pooled = (s - n_pad * pad_value[:, 0, 0, :].astype(STATS_DTYPE)
                      ) / count[:, 0, 0, :]
        else:
            m = mask[..., None].astype(STATS_DTYPE)
            pooled = jnp.sum(x.astype(STATS_DTYPE) * m, axis=(1, 2)) / (
                jnp.maximum(jnp.sum(m, axis=(1, 2)), 1.0))
        pooled = pooled.astype(self.dtype)
        h = nn.relu(nn.Dense(max(1, self.channels // self.ratio), dtype=self.dtype)(pooled))
        h = nn.relu(nn.Dense(self.channels, dtype=self.dtype)(h))
        gate = nn.sigmoid(h)[:, None, None, :]
        y = x * gate.astype(x.dtype)
        if depad:
            return y, pad_value * gate.astype(pad_value.dtype)
        return y


class BottleneckBlock(nn.Module):
    """One dilated bottleneck unit: [inorm] - act - 1x1 down - [inorm] - act -
    3x3 dilated - [inorm] - act - 1x1 up - SE - residual
    (reference ResNet inner loop, deepinteract_modules.py:1060-1086).

    ``depad`` selects the pad-value-tracking fast path (requires mask,
    count AND an incoming ``pad_value``): instead of re-zeroing the padded
    region after every op, the block tracks the single per-channel value
    all padded pixels hold and pushes it through each op in closed form,
    so every statistic runs as an UNMASKED reduction with a closed-form
    pad correction.

    r10 revision (the attribution burn-down, ROADMAP item 2): the r5
    design pushed an arbitrary [B, 1, 1, C] pad value through each 1x1
    conv as a tiny matvec — 112 such launches per decoder forward, the
    top re-mask-class sink in the PR-7 census×time reconciliation. Now
    the invariant is "every conv sees ZERO padded pixels": the zeroing
    multiply is fused into the elu that already precedes each conv (a
    mask broadcast riding an existing elementwise pass — no extra kernel,
    unlike the r4 standalone re-masks), so a 1x1 conv's pad value out is
    just its bias (:class:`BiasConv1x1`, param-only) and the only
    data-dependent pad values left are the norm affines and the SE gate —
    pure fused elementwise arithmetic on [B, 1, 1, C]. The mask
    materializes in four FUSED multiplies per inorm block (after each of
    the three norms' elu and after the 3x3's boundary mixing) instead of
    the r5 two-plus-112-matvecs. Statistics are identical up to float
    association (padding-invariance tests are the oracle).

    Fast path returns ``(out, pad_value_out)``; plain path returns the
    masked tensor as before."""

    channels: int
    dilation: int
    use_inorm: bool
    dtype: Any = FLOAT32
    depad: bool = False
    # True only under remat_policy='convs' (see _tag_conv).
    tag_convs: bool = False

    @nn.compact
    def __call__(self, x, mask=None, count=None, pad_value=None):
        half = self.channels // 2
        tag = self.tag_convs
        fast = (self.depad and mask is not None and count is not None
                and pad_value is not None)
        residual, pv_res = x, pad_value
        pv = pad_value
        if self.use_inorm:
            if fast:
                x, pv = InstanceNorm(self.channels, name="inorm_1")(
                    x, mask, count=count, pad_value=pv, depad=True)
            else:
                x = InstanceNorm(self.channels, name="inorm_1")(x, mask)
        if fast:
            # Zero the pad in the SAME elementwise pass as the elu: the
            # 1x1 then sees zero pads and its pad value out is its bias
            # (BiasConv1x1) — no pad-value matvec.
            x = nn.elu(x) * mask[..., None].astype(x.dtype)
            x, pv = BiasConv1x1(half, dtype=self.dtype, name="conv2d_1")(x)
            x = _tag_conv(x, tag)
            if self.use_inorm:
                # The post-norm pad value is discarded: the pre-3x3 mask
                # below re-zeroes the pad anyway. Only the STATISTICS
                # correction needs ``pv`` (= conv2d_1's bias).
                x, _ = InstanceNorm(half, name="inorm_2")(
                    x, mask, count=count, pad_value=pv, depad=True)
            # The dilated 3x3 must see the reference's zero boundary, so
            # the padded region is zeroed right before it (fused, again).
            x = nn.elu(x) * mask[..., None].astype(x.dtype)
        else:
            x = nn.elu(x)
            x = _tag_conv(
                nn.Conv(half, (1, 1), dtype=self.dtype, name="conv2d_1")(x),
                tag)
            if self.use_inorm:
                x = InstanceNorm(half, name="inorm_2")(x, mask)
            x = nn.elu(x)
            if mask is not None:
                # Zero the padded region before the only spatially-mixing
                # conv: conv biases make padded pixels nonzero mid-block,
                # and a dilated 3x3 would smear them into real pixels near
                # the pad boundary. With this mask, padded buckets match
                # the reference's unpadded zero-boundary conv behavior
                # exactly.
                x = x * mask[..., None].astype(x.dtype)
        x = _tag_conv(nn.Conv(
            half, (3, 3), kernel_dilation=(self.dilation, self.dilation),
            padding=self.dilation, dtype=self.dtype, name="conv2d_2",
        )(x), tag)
        if fast:
            # The 3x3 mixed valid values into the boundary band of the
            # pad, so the pad value is no longer uniform; re-zeroing
            # restores pad_value == 0 and makes inorm_3's statistics
            # unmasked-exact (n_pad * 0 correction).
            x = x * mask[..., None].astype(x.dtype)
            if self.use_inorm:
                x, _ = InstanceNorm(half, name="inorm_3")(
                    x, mask, count=count,
                    pad_value=jnp.zeros_like(x[:, :1, :1, :]), depad=True)
                # The norm affine re-filled the pad; zero it in the elu
                # pass so conv2d_3's pad value is its bias.
                x = nn.elu(x) * mask[..., None].astype(x.dtype)
            else:
                # Pads are exactly zero and elu(0) == 0 — no mask needed.
                x = nn.elu(x)
            x, pv = BiasConv1x1(self.channels, dtype=self.dtype,
                                name="conv2d_3")(x)
            x = _tag_conv(x, tag)
            x, pv = SEBlock(self.channels, dtype=self.dtype, name="se_block")(
                x, mask, count=count, pad_value=pv)
            return x + residual, pv + pv_res
        if self.use_inorm:
            # After the 3x3, boundary pad pixels mix valid values — the
            # general masked reduction is required.
            x = InstanceNorm(half, name="inorm_3")(x, mask)
        x = nn.elu(x)
        x = _tag_conv(nn.Conv(self.channels, (1, 1), dtype=self.dtype,
                              name="conv2d_3")(x), tag)
        x = SEBlock(self.channels, dtype=self.dtype, name="se_block")(x, mask)
        out = x + residual
        if mask is not None:
            out = out * mask[..., None].astype(out.dtype)
        return out


class DilationChunk(nn.Module):
    """One dilation cycle (the scan body when ``scan_chunks`` is on): the
    reference repeats this exact 4-block unit ``num_chunks`` times
    (deepinteract_modules.py:1060-1086). Returns the ``(carry, out)`` pair
    ``nn.scan`` expects; in depad mode the carry is ``(x, pad_value)`` so
    the tracked pad value survives across scan iterations."""

    channels: int
    dilation_cycle: Sequence[int]
    use_inorm: bool
    remat: bool = False
    dtype: Any = FLOAT32
    depad: bool = False
    remat_policy: str = "full"

    @nn.compact
    def __call__(self, carry, mask=None, count=None):
        # Block-granularity remat, matching the unrolled path's memory
        # behavior: each block stores only its input (plus, under the
        # 'convs' policy, its conv outputs) and recomputes inside.
        block_cls = (_remat_transform(self.remat_policy)(BottleneckBlock)
                     if self.remat else BottleneckBlock)
        tag = self.remat and self.remat_policy == "convs"
        if self.depad:
            x, pv = carry
        else:
            x, pv = carry, None
        for d in self.dilation_cycle:
            out = block_cls(
                self.channels, d, self.use_inorm, self.dtype, self.depad,
                tag, name=f"block_d{d}",
            )(x, mask, count, pv)
            x, pv = out if self.depad else (out, None)
        return ((x, pv) if self.depad else x), None


class DilatedResNet(nn.Module):
    """num_chunks x dilation_cycle bottleneck blocks (+2 optional extra
    blocks) with optional initial 1x1 projection
    (reference ResNet, deepinteract_modules.py:973-1106)."""

    channels: int
    num_chunks: int
    dilation_cycle: Sequence[int] = (1, 2, 4, 8)
    use_inorm: bool = False
    initial_projection: bool = False
    extra_blocks: bool = False
    remat: bool = False
    scan_chunks: bool = False
    dtype: Any = FLOAT32
    depad: bool = False
    remat_policy: str = "full"

    @nn.compact
    def __call__(self, x, mask=None, count=None, pad_value=None):
        # nn.remat preserves module naming, so remat and non-remat configs
        # share one param/checkpoint tree. Returns ``(x, pad_value_out)``
        # in depad mode (pad-value tracking), else ``(x, None)``.
        depad = (self.depad and mask is not None and count is not None
                 and pad_value is not None)
        block_cls = (_remat_transform(self.remat_policy)(BottleneckBlock)
                     if self.remat else BottleneckBlock)
        tag = self.remat and self.remat_policy == "convs"
        pv = pad_value if depad else None
        if self.initial_projection:
            # Depad contract (r10): the caller zeroed the pad in the
            # preceding fused elu pass, so the projection's pad value out
            # is its bias (BiasConv1x1) — no pad-value matvec. In the
            # plain masked mode the bias pad value is simply unused.
            x, pv_out = BiasConv1x1(self.channels, dtype=self.dtype,
                                    name="init_proj")(x)
            if depad:
                # Concrete [B, 1, 1, C]: the chunk scan carries the pad
                # value, and scan carries must keep a stable shape across
                # iterations (blocks return batch-dependent pad values).
                pv = jnp.broadcast_to(
                    pv_out, (x.shape[0], 1, 1, self.channels))
        if self.scan_chunks and self.num_chunks > 1:
            # Compile ONE cycle, run it num_chunks times: params stack on a
            # leading [num_chunks] axis under 'chunks/'. ``in_axes=
            # nn.broadcast`` feeds the same mask/count to every iteration.
            scan = nn.scan(
                DilationChunk,
                variable_axes={"params": 0},
                split_rngs={"params": True},
                length=self.num_chunks,
                in_axes=(nn.broadcast, nn.broadcast),
            )
            carry = (x, pv) if depad else x
            carry, _ = scan(
                self.channels, tuple(self.dilation_cycle), self.use_inorm,
                self.remat, self.dtype, depad, self.remat_policy,
                name="chunks",
            )(carry, mask, count)
            x, pv = carry if depad else (carry, None)
        else:
            for i in range(self.num_chunks):
                for d in self.dilation_cycle:
                    out = block_cls(
                        self.channels, d, self.use_inorm, self.dtype, depad,
                        tag, name=f"block_{i}_{d}",
                    )(x, mask, count, pv)
                    x, pv = out if depad else (out, None)
        if self.extra_blocks:
            for i in range(2):
                out = block_cls(
                    self.channels, 1, self.use_inorm, self.dtype, depad,
                    tag, name=f"extra_block_{i}",
                )(x, mask, count, pv)
                x, pv = out if depad else (out, None)
        return x, pv


class RegionalAttention(nn.Module):
    """Multi-head attention over a local region_size x region_size window
    (reference MultiHeadRegionalAttention, deepinteract_modules.py:1109-1152).

    TPU-first formulation: instead of the reference's Conv3d "stretch"
    weight trick, window extraction is ``jax.lax`` patch gathering via
    shifted pads — the math (softmax over the s^2 window per pixel) is
    identical.
    """

    channels: int
    d_k: int = 16
    num_heads: int = 4
    region_size: int = 3
    dropout_rate: float = 0.1
    dtype: Any = FLOAT32

    @nn.compact
    def __call__(self, x, mask=None, train: bool = False):
        b, hh, ww, _ = x.shape
        s = self.region_size
        if mask is not None:
            # Zeroing the padded region makes window slots that fall in the
            # pad behave exactly like the reference's zero-padded image
            # boundary (q/k/v are bias-free 1x1 convs, so qk = 0 there).
            x = x * mask[..., None].astype(x.dtype)
        q = nn.Conv(self.d_k, (1, 1), use_bias=False, dtype=self.dtype, name="q_layer")(x)
        k = nn.Conv(self.d_k, (1, 1), use_bias=False, dtype=self.dtype, name="k_layer")(x)
        v = nn.Conv(self.channels, (1, 1), use_bias=False, dtype=self.dtype, name="v_layer")(x)

        def patches(t):  # [B,H,W,C] -> [B,H,W,s*s,C]
            pad = s // 2
            tp = jnp.pad(t, ((0, 0), (pad, pad), (pad, pad), (0, 0)))
            shifts = [
                tp[:, dy : dy + hh, dx : dx + ww, :]
                for dy in range(s)
                for dx in range(s)
            ]
            return jnp.stack(shifts, axis=3)

        qk = patches(q) * patches(k)  # [B,H,W,s2,d_k]
        n_head = self.num_heads
        dk_per_head = self.d_k // n_head
        qk = qk.reshape(b, hh, ww, s * s, n_head, dk_per_head).sum(-1)  # [B,H,W,s2,n_head]
        # Softmax in f32 (bf16 exponentials lose too much), back to compute dtype.
        att = nn.softmax(
            qk.astype(STATS_DTYPE) / jnp.sqrt(STATS_DTYPE(self.d_k)), axis=3
        ).astype(qk.dtype)
        att = nn.Dropout(self.dropout_rate, deterministic=not train)(att)
        v_p = patches(v).reshape(b, hh, ww, s * s, n_head, self.channels // n_head)
        out = jnp.einsum("bhwsn,bhwsnc->bhwnc", att, v_p).reshape(b, hh, ww, self.channels)
        if mask is not None:
            out = out * mask[..., None]
        return out


class InteractionDecoder(nn.Module):
    """Full decoder head: 1x1 conv + inorm -> base dilated ResNet (inorm) ->
    phase-2 ResNet (+extra blocks) -> 1x1 conv to classes
    (ResNet2DInputWithOptAttention, deepinteract_modules.py:1155-1248).

    ``pair_tensor`` is either the materialized ``[B, L1, L2, 2C]``
    interaction tensor or a :class:`~deepinteract_tpu.models.stem.
    PairFactors` bundle — the factorized stem computes the entry 1x1 conv
    from per-chain features without ever materializing the 2C tensor
    (models/stem.py). Both paths share one param tree (``conv2d_1``)."""

    cfg: DecoderConfig

    @nn.compact
    def __call__(self, pair_tensor, mask=None, train: bool = False):
        cfg = self.cfg
        dt = cfg.dtype
        if isinstance(pair_tensor, PairFactors) and mask is None:
            mask = pair_tensor.pair_mask()
        # Valid-pixel count, computed ONCE and shared by every de-padded
        # statistic in the stack ([B, 1, 1, 1] float32).
        depad = mask is not None and cfg.depad_stats
        count = pv = None
        if depad:
            count = jnp.maximum(
                jnp.sum(mask.astype(STATS_DTYPE), axis=(1, 2),
                        keepdims=True)[..., None], 1.0)
        # The entry conv: factorized (two per-chain matmuls + broadcast
        # add, O(L*C^2), no 2C tensor) or materialized (the plain 1x1).
        x = PairStem1x1(cfg.num_channels, dtype=dt,
                        name="conv2d_1")(pair_tensor)
        if depad:
            # Entry mask: the incoming pair tensor's padded pixels are
            # arbitrary (GT features of padded nodes), so zero them here —
            # every later op tracks the pad value in closed form instead
            # of re-masking (see BottleneckBlock).
            x = x * mask[..., None].astype(x.dtype)
            pv = jnp.zeros_like(x[:, :1, :1, :])
            x, _ = InstanceNorm(cfg.num_channels, name="inorm_1")(
                x, mask, count=count, pad_value=pv, depad=True)
            # Zero the pad again in the elu pass (fused): base_resnet's
            # initial projection then sees zero pads and its pad value
            # out is its bias (BiasConv1x1 contract, r10).
            x = nn.elu(x) * mask[..., None].astype(x.dtype)
        else:
            x = nn.elu(InstanceNorm(cfg.num_channels, name="inorm_1")(x, mask))

        x, pv = DilatedResNet(
            cfg.num_channels, cfg.num_chunks, cfg.dilation_cycle,
            use_inorm=True, initial_projection=True, remat=cfg.remat,
            scan_chunks=cfg.scan_chunks, dtype=dt, depad=cfg.depad_stats,
            remat_policy=cfg.remat_policy, name="base_resnet",
        )(x, mask, count, pv)
        if pv is not None:
            # Inter-stage handoff under depad: zero the pad in the elu
            # pass so phase2's initial projection keeps the zero-pads-in
            # contract.
            x = nn.elu(x) * mask[..., None].astype(x.dtype)
            pv = jnp.zeros_like(pv)
        else:
            x = nn.elu(x)
        if cfg.use_attention:
            x = nn.elu(RegionalAttention(
                cfg.num_channels, num_heads=cfg.num_attention_heads,
                region_size=cfg.region_size, dropout_rate=cfg.dropout_rate,
                dtype=dt, name="mha2d_1",
            )(x, mask, train))
            if pv is not None:
                # RegionalAttention masks its output, so pads are zero again.
                pv = jnp.zeros_like(pv)

        x, pv = DilatedResNet(
            cfg.num_channels, 1, cfg.dilation_cycle,
            use_inorm=False, initial_projection=True, extra_blocks=True,
            remat=cfg.remat, dtype=dt, depad=cfg.depad_stats,
            remat_policy=cfg.remat_policy, name="phase2_resnet",
        )(x, mask, count, pv)
        x = nn.elu(x)
        if cfg.use_attention:
            x = nn.elu(RegionalAttention(
                cfg.num_channels, num_heads=cfg.num_attention_heads,
                region_size=cfg.region_size, dropout_rate=cfg.dropout_rate,
                dtype=dt, name="mha2d_2",
            )(x, mask, train))

        # phase2 (1 chunk + 2 extra blocks) stays unrolled: scanning a
        # length-1 cycle would change its tree for no compile saving.
        # Positive-class bias -7 => initial positive probability ~0.001
        # (reference reset_parameters, deepinteract_modules.py:1219-1226).
        def final_bias(key, shape, dtype=OUTPUT_DTYPE):
            bias = jnp.zeros(shape, dtype)
            return bias.at[1].set(-7.0)

        # Logits in float32 regardless of the activation dtype.
        logits = nn.Conv(cfg.num_classes, (1, 1), bias_init=final_bias,
                         name="phase2_conv")(x.astype(OUTPUT_DTYPE))
        if mask is not None:
            logits = logits * mask[..., None]
        return logits


# ---------------------------------------------------------------------------
# Param-tree conversion between the unrolled (r3 / torch-import natural) and
# scanned (stacked) base-ResNet layouts. Only 'base_resnet' differs; both
# directions are exact (stack/unstack of the same leaves).
# ---------------------------------------------------------------------------


def stack_chunk_params(decoder_params, num_chunks: int,
                       dilation_cycle: Sequence[int] = (1, 2, 4, 8)):
    """Unrolled decoder subtree (``base_resnet/block_{i}_{d}/...``) ->
    scanned layout (``base_resnet/chunks/block_d{d}/...`` with a leading
    [num_chunks] axis on every leaf)."""
    import jax

    out = dict(decoder_params)
    base = dict(out["base_resnet"])
    chunks: dict = {}
    for d in dilation_cycle:
        per_chunk = [base.pop(f"block_{i}_{d}") for i in range(num_chunks)]
        chunks[f"block_d{d}"] = jax.tree_util.tree_map(
            lambda *leaves: jnp.stack(leaves, axis=0), *per_chunk
        )
    base["chunks"] = chunks
    out["base_resnet"] = base
    return out


def unstack_chunk_params(decoder_params, num_chunks: int,
                         dilation_cycle: Sequence[int] = (1, 2, 4, 8)):
    """Inverse of :func:`stack_chunk_params`."""
    import jax

    out = dict(decoder_params)
    base = dict(out["base_resnet"])
    chunks = base.pop("chunks")
    for d in dilation_cycle:
        stacked = chunks[f"block_d{d}"]
        for i in range(num_chunks):
            base[f"block_{i}_{d}"] = jax.tree_util.tree_map(
                lambda leaf, _i=i: leaf[_i], stacked
            )
    out["base_resnet"] = base
    return out
