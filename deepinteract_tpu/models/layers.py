"""Shared building blocks: init scheme, masked normalization, residual blocks.

The reference normalizes node/edge features with ``nn.BatchNorm1d`` over the
concatenation of all graphs in a batch (``deepinteract_modules.py:605-613``).
Our graphs are padded, so batch statistics must be computed over *valid*
elements only — hence the masked BatchNorm here. LayerNorm ('layer' mode,
reference ``norm_to_apply``) is positionwise and needs no masking.
"""

from __future__ import annotations

from typing import Any, Callable, Optional, Sequence

import jax
import jax.numpy as jnp
from flax import linen as nn

from deepinteract_tpu.models.policy import FLOAT32, STATS_DTYPE


def glorot_orthogonal(scale: float = 2.0) -> Callable:
    """Orthogonal init rescaled to Glorot variance (reference
    ``glorot_orthogonal``, deepinteract_utils.py:47-52): W <- W * sqrt(scale /
    ((fan_in + fan_out) * var(W))) applied to an (approximately) orthogonal
    matrix produced by Newton-Schulz iteration — see the comment below for
    why QR is avoided.
    """
    import math

    def init(key, shape, dtype=FLOAT32):
        if len(shape) < 2:
            raise ValueError("glorot_orthogonal requires >=2D shapes")
        rows = math.prod(shape[:-1])
        cols = shape[-1]
        # Orthogonalize via Newton-Schulz iteration (Y <- 1.5 Y - 0.5 Y Y^T Y)
        # instead of QR: pure matmuls, so it compiles instantly on every
        # backend (XLA builds a fresh QR kernel per parameter shape, which
        # made init take minutes on CPU, and callbacks are unsupported on
        # some TPU plugins). Exactness of orthogonality is immaterial here —
        # the Glorot variance rescale below dominates the statistics.
        a = jax.random.normal(key, (max(rows, cols), min(rows, cols)))
        y = a / jnp.linalg.norm(a)  # all singular values <= 1

        def ns_step(y, _):
            return 1.5 * y - 0.5 * y @ (y.T @ y), None

        y, _ = jax.lax.scan(ns_step, y, None, length=48)
        if rows < cols:
            y = y.T
        w = y.reshape(shape)
        var = jnp.maximum(jnp.var(w), 1e-12)
        return (w * jnp.sqrt(scale / ((rows + cols) * var))).astype(dtype)

    return init


def uniform_sqrt3() -> Callable:
    """U(-sqrt(3), sqrt(3)) — reference node-index embedding init
    (deepinteract_modules.py:183)."""

    def init(key, shape, dtype=FLOAT32):
        s = jnp.sqrt(3.0)
        return jax.random.uniform(key, shape, dtype, minval=-s, maxval=s)

    return init


class GODense(nn.Module):
    """Dense layer with glorot_orthogonal kernel init and zero bias.

    ``dtype`` is the flax compute dtype (params stay float32 — the dtype
    policy's param_dtype); None keeps flax promotion, i.e. float32."""

    features: int
    use_bias: bool = True
    scale: float = 2.0
    dtype: Any = None

    @nn.compact
    def __call__(self, x):
        return nn.Dense(
            self.features,
            use_bias=self.use_bias,
            kernel_init=glorot_orthogonal(self.scale),
            bias_init=nn.initializers.zeros,
            dtype=self.dtype,
        )(x)


class MaskedBatchNorm(nn.Module):
    """BatchNorm over valid elements of arbitrarily many leading axes.

    Equivalent to torch ``BatchNorm1d`` applied to the flattened list of real
    nodes/edges in a batch (the reference's usage), with running statistics in
    the ``batch_stats`` collection. ``mask`` broadcasts against all but the
    channel axis.
    """

    use_running_average: Optional[bool] = None
    momentum: float = 0.1  # torch convention: new = (1-m)*old + m*batch
    epsilon: float = 1e-5

    @nn.compact
    def __call__(self, x, mask, use_running_average: Optional[bool] = None):
        use_ra = nn.merge_param(
            "use_running_average", self.use_running_average, use_running_average
        )
        ch = x.shape[-1]
        ra_mean = self.variable("batch_stats", "mean", lambda: jnp.zeros(ch))
        ra_var = self.variable("batch_stats", "var", lambda: jnp.ones(ch))
        scale = self.param("scale", nn.initializers.ones, (ch,))
        bias = self.param("bias", nn.initializers.zeros, (ch,))

        # Statistics always accumulate in float32 (the policy's stats
        # dtype): bf16 sums over thousands of nodes/edges lose mantissa.
        # Under f32 inputs every cast below is the identity, so the f32
        # path's numerics are unchanged.
        xf = x.astype(STATS_DTYPE)
        if use_ra:
            mean, var = ra_mean.value, ra_var.value
        else:
            m = jnp.broadcast_to(mask[..., None], x.shape).astype(STATS_DTYPE)
            count = jnp.maximum(jnp.sum(m), 1.0)
            axes = tuple(range(x.ndim - 1))
            mean = jnp.sum(xf * m, axis=axes) / count
            var = jnp.sum(m * (xf - mean) ** 2, axis=axes) / count
            if not self.is_initializing():
                ra_mean.value = (1 - self.momentum) * ra_mean.value + self.momentum * mean
                # torch tracks the unbiased variance in running stats
                unbiased = var * count / jnp.maximum(count - 1.0, 1.0)
                ra_var.value = (1 - self.momentum) * ra_var.value + self.momentum * unbiased
        y = (xf - mean) * jax.lax.rsqrt(var + self.epsilon) * scale + bias
        # Zero padded slots (don't pass raw values through): downstream code
        # may read intermediate features without re-masking.
        return jnp.where(mask[..., None], y, 0.0).astype(x.dtype)


class FeatureNorm(nn.Module):
    """'batch' or 'layer' normalization switch (reference ``norm_to_apply``,
    deepinteract_modules.py:605-613)."""

    norm_type: str = "batch"
    dtype: Any = None

    @nn.compact
    def __call__(self, x, mask, train: bool = False):
        if self.norm_type == "layer":
            # flax LayerNorm computes its statistics in float32 internally;
            # dtype only sets the output/affine compute dtype.
            return nn.LayerNorm(dtype=self.dtype)(x)
        return MaskedBatchNorm()(x, mask, use_running_average=not train)


class ResBlock(nn.Module):
    """Conformation-module residual block (deepinteract_modules.py:455-497):
    x + (Linear-Norm-SiLU) x3, with the *same* norm instance reused at all
    three positions (a reference quirk: one ``norm_layer`` object appears
    three times in its ModuleList, sharing parameters and running stats)."""

    hidden: int
    norm_type: str = "batch"
    dtype: Any = None

    @nn.compact
    def __call__(self, x, mask, train: bool = False):
        shared_norm = FeatureNorm(self.norm_type, dtype=self.dtype,
                                  name="shared_norm")
        h = x
        for i in range(3):
            h = GODense(self.hidden, dtype=self.dtype, name=f"linear_{i}")(h)
            h = shared_norm(h, mask, train=train)
            h = nn.silu(h)
        return x + h


class MLP(nn.Module):
    """Transformer FFN: Dense(2C, no bias) - SiLU - Dropout - Dense(C, no
    bias) (reference node/edge_feats_MLP, deepinteract_modules.py:628-650)."""

    hidden: int
    dropout_rate: float = 0.1
    dtype: Any = None

    @nn.compact
    def __call__(self, x, train: bool = False):
        h = GODense(self.hidden * 2, use_bias=False, dtype=self.dtype)(x)
        h = nn.silu(h)
        h = nn.Dropout(self.dropout_rate, deterministic=not train)(h)
        return GODense(self.hidden, use_bias=False, dtype=self.dtype)(h)
