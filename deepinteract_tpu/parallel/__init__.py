"""Parallelism: device meshes, sharded train steps, collectives.

Replaces the reference's Lightning DDP / torch.distributed NCCL stack
(SURVEY.md §2.6) with jax.sharding meshes: a ``data`` axis over protein
complexes (DDP equivalent) and a ``pair`` axis sharding the L1 x L2
interaction map (context parallelism over the pair dimension — the
distributed generalization of the reference's 256x256 subsequencing tiles).
"""

from deepinteract_tpu.parallel.mesh import (  # noqa: F401
    make_mesh,
    mesh_context,
    replicate,
    shard_batch,
    shard_stacked_batch,
)
from deepinteract_tpu.parallel.multihost import (  # noqa: F401
    host_local_array,
    initialize_distributed,
    is_primary_host,
    shard_filenames_for_host,
)
from deepinteract_tpu.parallel.train import (  # noqa: F401
    make_sharded_eval_step,
    make_sharded_multi_eval_step,
    make_sharded_multi_step,
    make_sharded_train_step,
)
