"""Multi-host (multi-process) initialization and per-host data sharding.

The reference scales across nodes with Lightning DDP over torch.distributed
(``--num_compute_nodes`` -> ``args.num_nodes``, lit_model_train.py:217,226;
NCCL backend). The TPU-native equivalent needs no custom communication
layer: ``jax.distributed.initialize`` wires every host into one runtime,
``jax.devices()`` then spans the whole slice/pod, and the same GSPMD-jitted
step (``parallel/train.py``) runs unchanged — XLA routes collectives over
ICI within a slice and DCN across slices.

What the framework must still do itself (this module):
* initialize the distributed runtime idempotently, honoring both TPU
  auto-detection and explicit coordinator env vars;
* shard the *data pipeline* per host — each process feeds only its own
  shard of the complex list (the DistributedSampler analog Lightning
  injects, SURVEY.md §2.6) — while batches keep their global meaning under
  ``jax.make_array_from_process_local_data``.

Single-host callers can ignore this module entirely; everything degrades
to process_count() == 1.
"""

from __future__ import annotations

from typing import Optional, Sequence

import jax


def initialize_distributed(
    coordinator_address: Optional[str] = None,
    num_processes: Optional[int] = None,
    process_id: Optional[int] = None,
) -> int:
    """Idempotently initialize the multi-process JAX runtime.

    On TPU pods all arguments auto-detect from the environment; elsewhere
    pass coordinator/num_processes/process_id explicitly (or set the
    standard JAX_COORDINATOR_ADDRESS etc.). Returns the process index.
    Safe to call when already initialized or single-process.

    Must run before anything touches the XLA backend (even
    ``jax.process_count()`` initializes it, after which distributed init
    is rejected) — call it first thing in the training entry point.
    """
    # Idempotency via the distributed client itself: process_count() would
    # initialize the XLA backend and make a later initialize() impossible.
    state = getattr(jax.distributed, "global_state", None)
    if state is not None and getattr(state, "client", None) is not None:
        return jax.process_index()  # already initialized
    explicit = any(
        v is not None for v in (coordinator_address, num_processes, process_id)
    )
    try:
        jax.distributed.initialize(
            coordinator_address=coordinator_address,
            num_processes=num_processes,
            process_id=process_id,
        )
    except (RuntimeError, ValueError):
        if explicit:
            # The caller asked for a specific topology; degrading to
            # single-process here would silently split-brain the run.
            raise
        # Auto-detection found no distributed environment: single-process.
    return jax.process_index()


def shard_filenames_for_host(
    filenames: Sequence[str],
    process_index: Optional[int] = None,
    process_count: Optional[int] = None,
) -> list:
    """This host's contiguous shard of the (already shuffled) complex list
    — the DistributedSampler analog. Every host must receive the same
    ``filenames`` ordering (same seed) for shards to be disjoint."""
    pi = jax.process_index() if process_index is None else process_index
    pc = jax.process_count() if process_count is None else process_count
    if pc <= 1:
        return list(filenames)
    # Drop the remainder so every host runs the same number of steps (a
    # straggler host would deadlock collectives at epoch end).
    per_host = len(filenames) // pc
    start = pi * per_host
    return list(filenames[start : start + per_host])


def is_primary_host() -> bool:
    """True on the process that should write checkpoints/logs (rank-0
    semantics of the reference's Lightning callbacks)."""
    return jax.process_index() == 0
