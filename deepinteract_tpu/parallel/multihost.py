"""Multi-host (multi-process) initialization and per-host data sharding.

The reference scales across nodes with Lightning DDP over torch.distributed
(``--num_compute_nodes`` -> ``args.num_nodes``, lit_model_train.py:217,226;
NCCL backend). The TPU-native equivalent needs no custom communication
layer: ``jax.distributed.initialize`` wires every host into one runtime,
``jax.devices()`` then spans the whole slice/pod, and the same GSPMD-jitted
step (``parallel/train.py``) runs unchanged — XLA routes collectives over
ICI within a slice and DCN across slices.

What the framework must still do itself (this module):
* initialize the distributed runtime idempotently, honoring both TPU
  auto-detection and explicit coordinator env vars;
* shard the *data pipeline* per host — each process feeds only its own
  shard of the complex list (the DistributedSampler analog Lightning
  injects, SURVEY.md §2.6) — while batches keep their global meaning under
  ``jax.make_array_from_process_local_data``.

Single-host callers can ignore this module entirely; everything degrades
to process_count() == 1.
"""

from __future__ import annotations

import itertools
from typing import Optional, Sequence

import jax


def initialize_distributed(
    coordinator_address: Optional[str] = None,
    num_processes: Optional[int] = None,
    process_id: Optional[int] = None,
) -> int:
    """Idempotently initialize the multi-process JAX runtime.

    On TPU pods all arguments auto-detect from the environment; elsewhere
    pass coordinator/num_processes/process_id explicitly (or set the
    standard JAX_COORDINATOR_ADDRESS etc.). Returns the process index.
    Safe to call when already initialized or single-process.

    Must run before anything touches the XLA backend (even
    ``jax.process_count()`` initializes it, after which distributed init
    is rejected) — call it first thing in the training entry point.
    """
    import os

    explicit = any(
        v is not None for v in (coordinator_address, num_processes, process_id)
    )
    # Idempotency via the distributed client itself: process_count() would
    # initialize the XLA backend and make a later initialize() impossible.
    # jax._src is internal and may move across JAX upgrades — it is a
    # best-effort fast path only; the public fallback below catches the
    # "already initialized" RuntimeError from jax.distributed.initialize.
    try:
        from jax._src import distributed as _dist

        if getattr(_dist.global_state, "client", None) is not None:
            return jax.process_index()  # already initialized
    except (ImportError, AttributeError):  # pragma: no cover - jax version
        pass
    if explicit or os.environ.get("JAX_COORDINATOR_ADDRESS"):
        # A deliberate multi-process run. CPU backends need a collectives
        # implementation AND the platform pinned through jax.config (the
        # env var alone does not stop a registered accelerator PJRT plugin
        # from claiming the default backend, and a backend built before
        # the distributed client exists is permanently single-process).
        if os.environ.get("JAX_PLATFORMS", "").startswith("cpu"):
            jax.config.update("jax_platforms", "cpu")
            jax.config.update("jax_cpu_collectives_implementation", "gloo")
        try:
            from jax._src import xla_bridge as _xb

            if _xb._backends:
                _xb._clear_backends()
        except Exception:  # pragma: no cover - internal API best effort
            pass
    try:
        jax.distributed.initialize(
            coordinator_address=coordinator_address,
            num_processes=num_processes,
            process_id=process_id,
        )
    except (RuntimeError, ValueError) as exc:
        if "already initialized" in str(exc).lower():
            return jax.process_index()  # idempotent re-entry (public path)
        if explicit:
            # The caller asked for a specific topology; degrading to
            # single-process here would silently split-brain the run.
            raise
        # Auto-detection found no distributed environment: single-process.
    return jax.process_index()


def shard_filenames_for_host(
    filenames: Sequence[str],
    process_index: Optional[int] = None,
    process_count: Optional[int] = None,
) -> list:
    """This host's shard of a work list (same ``filenames`` ordering on
    every host -> disjoint shards; remainder wrapped like torch's
    DistributedSampler so shard lengths match and nothing is permanently
    excluded).

    Use for embarrassingly-parallel per-host work WITHOUT global
    collectives — bulk featurization, dataset building, analysis sweeps.
    Do NOT use it to split a *training* file list: per-host lists give
    hosts different bucket distributions/batch shapes and deadlock the
    global train collectives — training shards through the coordinated
    ``BucketedLoader(shard=(process_index, process_count))`` plan instead
    (data/loader.py, wired in cli/train.py)."""
    pi = jax.process_index() if process_index is None else process_index
    pc = jax.process_count() if process_count is None else process_count
    if pc <= 1:
        return list(filenames)
    names = list(filenames)
    per_host = -(-len(names) // pc)  # ceil
    padded = list(itertools.islice(itertools.cycle(names), per_host * pc))
    start = pi * per_host
    return padded[start : start + per_host]


def assert_same_across_hosts(values, fail_message: str) -> None:
    """Assert a small host-side value agrees on every process (no-op
    single-process).

    The host-agreement primitive behind ``Trainer.evaluate``'s
    first-batch/loader-length fingerprint check. Only call it from code
    paths that EVERY host executes at the same point (it is a
    collective); asymmetric paths — e.g. an abort only some hosts take —
    must rely on replicated-by-construction values instead (see the
    non-finite guard: robustness/guards.py branches on the pmean'd
    loss/grads, so its decisions agree without a collective). Costs one
    tiny collective; keep it OFF hot paths."""
    if jax.process_count() <= 1:
        return
    import numpy as np
    from jax.experimental import multihost_utils

    multihost_utils.assert_equal(
        np.asarray(values, dtype=np.float32), fail_message=fail_message
    )


def _coordination_client():
    """The jax distributed coordination-service client (host-side KV
    store + barriers), or None when the runtime is uninitialized or the
    jax version moved the handle."""
    try:
        from jax._src import distributed

        return distributed.global_state.client
    except Exception:  # pragma: no cover - jax internals moved
        return None


def can_agree() -> bool:
    """True when :func:`agree_any_flag` has a working transport: a real
    multi-process runtime with a live coordination client."""
    return jax.process_count() > 1 and _coordination_client() is not None


def agree_any_flag(tag: str, local_flag: bool,
                   timeout_s: float = 120.0) -> bool:
    """Host-0-decides OR over one boolean per host.

    The transport is the coordination-service KV store — host-side RPC,
    no device collective — so it is safe from a loader prefetch thread
    while the main thread is mid-train-step collective (a device
    collective issued there could interleave against the step's and
    deadlock the mesh). Every host publishes its flag under ``tag``;
    host 0 reads all of them, publishes the OR as the verdict, and every
    host returns that same verdict. ``tag`` must be unique per decision
    (the KV store is append-only for a run). Single-process: the local
    flag IS the verdict."""
    if jax.process_count() <= 1:
        return bool(local_flag)
    client = _coordination_client()
    if client is None:
        raise RuntimeError(
            "agree_any_flag needs the jax coordination client "
            "(jax.distributed.initialize ran?) — refusing to guess a "
            "cross-host decision")
    timeout_ms = max(1, int(timeout_s * 1000))
    client.key_value_set(f"{tag}/h{jax.process_index()}",
                         "1" if local_flag else "0")
    if jax.process_index() == 0:
        verdict = bool(local_flag)
        for peer in range(1, jax.process_count()):
            peer_flag = client.blocking_key_value_get(
                f"{tag}/h{peer}", timeout_ms)
            verdict = verdict or peer_flag == "1"
        client.key_value_set(f"{tag}/verdict", "1" if verdict else "0")
        return verdict
    return client.blocking_key_value_get(f"{tag}/verdict",
                                         timeout_ms) == "1"


def is_primary_host() -> bool:
    """True on the process that should write checkpoints/logs (rank-0
    semantics of the reference's Lightning callbacks)."""
    return jax.process_index() == 0


def exit_barrier(tag: str = "exit") -> None:
    """Cross-host rendezvous + coordinated distributed shutdown before
    process exit; no-op single-process.

    Hosts leave ``cli.train`` at different times (rank-0's checkpoint/
    CSV/compile-cache atexit work vs the peers' immediate return —
    widest on the preemption path), and jax's OWN atexit
    ``distributed.shutdown`` runs a two-sided coordination-service
    barrier with a timeout: when one host's interpreter teardown is
    slow, the other times out at that barrier and the runtime
    **aborts the process** ("Shutdown barrier in coordination service
    has failed" → SIGABRT; observed flakily in the 2-proc
    kill-after-save chaos test). The fix is to run the handshake while
    the hosts are still ALIGNED: an explicit collective rendezvous,
    then ``jax.distributed.shutdown()`` immediately — which also makes
    jax's atexit hook a no-op, so per-host teardown skew afterwards no
    longer involves the coordination service at all. Best-effort: a
    failure here must not turn a finished (or cleanly preempted) run
    into a crash. No jax collectives may run after this call."""
    if jax.process_count() <= 1:
        return
    import logging

    try:
        from jax.experimental import multihost_utils

        multihost_utils.sync_global_devices(tag)
    except Exception as exc:  # pragma: no cover - peer already gone
        logging.getLogger(__name__).warning(
            "exit barrier %r failed (peer already down?): %s", tag, exc)
    try:
        jax.distributed.shutdown()
    except Exception as exc:  # pragma: no cover - best effort
        logging.getLogger(__name__).warning(
            "distributed shutdown after barrier %r failed: %s", tag, exc)


def host_local_array(x):
    """A global ``jax.Array`` -> this host's local numpy view.

    * fully-addressable (single-process, or host-local) arrays: as-is;
    * replicated multi-host arrays (losses, params): the first local
      shard, which holds the full value;
    * batch-sharded multi-host arrays (eval outputs): local shards
      reassembled — concatenated along axis 0 (the complexes THIS host fed
      in) and, when a second mesh axis (e.g. 'pair') partitions axis 1,
      along axis 1 as well. Distinct-index duplicates from replicating
      axes are dropped.

    Raises if the local shards cannot reconstruct full rows (axis 1+
    partitioned across *hosts*): a silent partial view would corrupt
    metrics downstream — gather on device before reading instead.
    """
    import numpy as np

    if getattr(x, "is_fully_addressable", True):
        return np.asarray(x)
    # Deduplicate by each shard's START OFFSETS, not its raw index tuple:
    # slices are unhashable before Python 3.12, and the offsets are the
    # identity the grid reassembly needs anyway (replicating axes yield
    # duplicate offsets — dropped here by construction).
    shards = {
        tuple((sl.start or 0) for sl in s.index): np.asarray(s.data)
        for s in x.addressable_shards
    }
    if len(shards) == 1:  # replicated (or scalar): one distinct index
        return next(iter(shards.values()))
    # GSPMD shards tile a regular grid; reassemble this host's sub-grid
    # along every axis via np.block. Axes partitioned across *hosts* come
    # back smaller than the global dim — callers that need full coverage
    # must validate the returned shape (Trainer.evaluate does).
    starts = [
        sorted({idx[a] for idx in shards}) for a in range(x.ndim)
    ]
    pos = [{st: i for i, st in enumerate(s)} for s in starts]
    blocks = np.empty([len(s) for s in starts], dtype=object)
    for idx, data in shards.items():
        blocks[tuple(pos[a][idx[a]] for a in range(x.ndim))] = data
    if any(b is None for b in blocks.ravel()):
        raise ValueError(
            "host_local_array: local shards do not tile a complete grid; "
            "gather on device before reading"
        )
    return np.block(blocks.tolist())
