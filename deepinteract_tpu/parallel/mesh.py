"""Mesh construction and sharding helpers.

The reference scales with one process per GPU under Lightning DDP
(``lit_model_train.py:226``); here a single process drives all local devices
through a ``jax.sharding.Mesh``, and multi-host pods join the same mesh via
``jax.distributed.initialize`` — collectives ride ICI within a slice and DCN
across slices without any NCCL/MPI-style process-group management.
"""

from __future__ import annotations

from typing import Optional, Sequence

import jax
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

DATA_AXIS = "data"
PAIR_AXIS = "pair"


def mesh_context(mesh: Mesh):
    """Version-portable ``with mesh_context(mesh):`` activation.

    ``jax.set_mesh`` (the current API) only exists from jax 0.6; older
    releases spell it ``jax.sharding.use_mesh`` (0.4.35+, experimental) or
    rely on ``Mesh`` itself being a context manager (the 0.4.x legacy
    global-mesh context). All three establish the ambient mesh the
    sharded-step helpers and tests need; callers must not depend on the
    newer API's extra behaviors (e.g. implicit out-sharding inference)."""
    if hasattr(jax, "set_mesh"):
        return jax.set_mesh(mesh)
    if hasattr(jax.sharding, "use_mesh"):
        return jax.sharding.use_mesh(mesh)
    return mesh


def make_mesh(
    num_data: Optional[int] = None,
    num_pair: int = 1,
    devices: Optional[Sequence] = None,
) -> Mesh:
    """Build a (data, pair) mesh over available devices.

    ``data`` is the DDP-equivalent axis over complexes; ``pair`` shards the
    interaction map's first residue dimension (context parallelism).
    """
    devices = list(devices if devices is not None else jax.devices())
    if num_data is None:
        num_data = len(devices) // num_pair
    used = num_data * num_pair
    if used > len(devices):
        raise ValueError(f"mesh {num_data}x{num_pair} needs {used} devices, have {len(devices)}")
    arr = np.asarray(devices[:used]).reshape(num_data, num_pair)
    return Mesh(arr, (DATA_AXIS, PAIR_AXIS))


def serving_mesh(shape: Sequence[int],
                 devices: Optional[Sequence] = None) -> Mesh:
    """Build the (data, pair) mesh one serving worker owns from its
    ``--mesh_shape`` ``(num_data, num_pair)`` pair — the same
    :func:`make_mesh` layout training uses, so a worker's pair-sharded
    decode partitions exactly like the training-time sharded step.
    Validates both axes explicitly (a worker must fail LOUDLY at startup
    on a topology its slice cannot provide, not at first decode)."""
    if len(shape) != 2:
        raise ValueError(f"serving mesh shape needs 2 axes, got {shape!r}")
    num_data, num_pair = int(shape[0]), int(shape[1])
    if num_data < 1 or num_pair < 1:
        raise ValueError(
            f"serving mesh axes must be >= 1, got {num_data}x{num_pair}")
    return make_mesh(num_data=num_data, num_pair=num_pair,
                     devices=devices)


def batch_sharding(mesh: Mesh) -> NamedSharding:
    """The per-step batch sharding ([B, ...] split over ``data``) — the
    ONE definition shared by batch placement (:func:`shard_batch`, the
    ``data/pipeline.py`` placement layer) and the sharded step functions'
    ``in_shardings`` (``parallel/train.py``), so a pre-placed batch can
    never disagree with what the step expects (no silent reshard)."""
    return NamedSharding(mesh, P(DATA_AXIS))


def stacked_batch_sharding(mesh: Mesh) -> NamedSharding:
    """[K, B, ...] scan-stack sharding: scan axis unsharded, batch axis
    split over ``data``. Same single-source-of-truth contract as
    :func:`batch_sharding`."""
    return NamedSharding(mesh, P(None, DATA_AXIS))


def _place(tree, mesh: Mesh, spec: P, replicated: bool = False,
           sharding: Optional[NamedSharding] = None):
    """Place a pytree with one sharding spec.

    Single-process: plain sharded ``device_put``. Multi-process (mesh
    spans hosts): each host contributes its *local* arrays as its shard of
    the global array (``jax.make_array_from_process_local_data``); for
    fully-replicated specs the global shape equals the local shape."""
    sharding = sharding if sharding is not None else NamedSharding(mesh, spec)
    if jax.process_count() > 1:
        return jax.tree_util.tree_map(
            lambda x: jax.make_array_from_process_local_data(
                sharding, np.asarray(x), np.shape(x) if replicated else None
            ),
            tree,
        )
    return jax.device_put(tree, sharding)


def shard_batch(batch, mesh: Mesh):
    """Place a stacked batch pytree with its leading axis split over
    ``data``. Multi-process: the global batch is the concatenation of the
    hosts' local batches, so a per-host batch of B complexes trains a
    global batch of ``B * process_count`` exactly like DDP — each host
    contributes (and transfers) only its LOCAL shard."""
    return _place(batch, mesh, P(DATA_AXIS), sharding=batch_sharding(mesh))


def shard_stacked_batch(stacked, mesh: Mesh):
    """Like :func:`shard_batch` for [K, B, ...] scan-stacked batches: the
    scan axis stays unsharded, the batch axis splits over ``data``."""
    return _place(stacked, mesh, P(None, DATA_AXIS),
                  sharding=stacked_batch_sharding(mesh))


def replicate(tree, mesh: Mesh):
    """Fully replicate a pytree (params/opt state) across the mesh, built
    multi-process from each host's (identical, same-seed) local copy."""
    return _place(tree, mesh, P(), replicated=True)
