"""Sharded training step: pjit-style data + pair-map parallelism.

GSPMD does the heavy lifting: the step function is the *same* pure
``train_step`` used on one chip; sharding annotations on its inputs make XLA
insert the gradient reduce (replacing DDP's allreduce) and the halo
exchanges for pair-axis-sharded decoder convolutions.
"""

from __future__ import annotations

from functools import partial
from typing import Optional

import jax
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from deepinteract_tpu.parallel.mesh import (
    batch_sharding,
    stacked_batch_sharding,
)
from deepinteract_tpu.training.steps import TrainState, train_step


def make_sharded_train_step(mesh: Mesh, weight_classes: bool = False, donate: bool = True,
                            guard: bool = False):
    """jit ``train_step`` with state replicated and the batch split over the
    ``data`` axis. Gradients become pmean automatically through the
    batch-mean loss under GSPMD.

    ``guard`` enables the non-finite step guard (robustness/guards.py).
    Under GSPMD the guarded ``lax.cond`` branches on the globally-reduced
    loss/grad-norm — replicated values, so every device and host takes the
    same branch; no extra collective is needed for agreement.

    Input contract: the batch's ``in_shardings`` comes from
    ``mesh.batch_sharding`` — the SAME constructor the placement layer
    (``data/pipeline.py``) uses — so a batch pre-placed on the loader's
    prefetch thread arrives with a matching sharding and is consumed
    as-is (no re-placement, no resharding copy); host numpy batches are
    placed by jit at dispatch exactly as before.
    """
    replicated = NamedSharding(mesh, P())
    batch_sharded = batch_sharding(mesh)

    step = partial(train_step, weight_classes=weight_classes, axis_name=None,
                   guard=guard)
    return jax.jit(
        step,
        in_shardings=(replicated, batch_sharded),
        out_shardings=(replicated, replicated),
        donate_argnums=(0,) if donate else (),
    )


def make_sharded_multi_step(mesh: Mesh, weight_classes: bool = False, donate: bool = True,
                            guard: bool = False):
    """Sharded :func:`deepinteract_tpu.training.steps.multi_train_step`:
    the stacked batch is [K, B, ...] with the scan axis unsharded and the
    batch axis split over ``data``. ``guard`` as in
    :func:`make_sharded_train_step` (per scanned step)."""
    from deepinteract_tpu.training.steps import multi_train_step

    replicated = NamedSharding(mesh, P())
    batch_sharded = stacked_batch_sharding(mesh)

    step = partial(multi_train_step, weight_classes=weight_classes, axis_name=None,
                   guard=guard)
    return jax.jit(
        step,
        in_shardings=(replicated, batch_sharded),
        out_shardings=(replicated, replicated),
        donate_argnums=(0,) if donate else (),
    )


def make_sharded_eval_step(mesh: Mesh, weight_classes: bool = False):
    from deepinteract_tpu.training.steps import eval_step

    replicated = NamedSharding(mesh, P())
    batch_sharded = batch_sharding(mesh)
    step = partial(eval_step, weight_classes=weight_classes)
    return jax.jit(
        step,
        in_shardings=(replicated, batch_sharded),
        out_shardings=None,
    )


def make_sharded_multi_eval_step(mesh: Mesh, weight_classes: bool = False):
    """Sharded :func:`deepinteract_tpu.training.steps.multi_eval_step`:
    stacked [K, B, ...] batches, scan axis unsharded, batch over ``data``."""
    from deepinteract_tpu.training.steps import multi_eval_step

    replicated = NamedSharding(mesh, P())
    batch_sharded = stacked_batch_sharding(mesh)
    step = partial(multi_eval_step, weight_classes=weight_classes)
    return jax.jit(
        step,
        in_shardings=(replicated, batch_sharded),
        out_shardings=None,
    )
