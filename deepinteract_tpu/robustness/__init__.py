"""Fault-tolerance layer: keep long unattended runs alive.

A production-scale training/serving system dies in one of a handful of
well-known ways — a NaN gradient poisons the weights, a TPU-pool
preemption kills the process mid-epoch, a transient network or subprocess
hiccup aborts a multi-hour feature build. Each failure mode gets a
dedicated, individually-testable module here:

* :mod:`guards` — on-device non-finite step guard (skip bad optimizer
  updates, count consecutive skips, abort with diagnostics past a budget);
* :mod:`preemption` — SIGTERM/SIGINT-safe training (clean checkpoint
  flush + verified ``--resume`` round trip);
* :mod:`retry` — exponential backoff with jitter and a deadline for
  flaky I/O and native tooling (downloads, compiles, HH-suite);
* :mod:`faults` — deterministic fault injection powering the chaos test
  suite (``tests/test_fault_tolerance.py``) and manual game-days;
* :mod:`artifacts` — durable persistence: atomic writes, SHA-256
  integrity sidecars with typed ``CorruptArtifact``/``StaleArtifact``
  verification, quarantine, and the orphaned-tmp sweep
  (``tests/test_artifacts.py``, ``cli/fsck.py``).

Everything is dependency-free (stdlib + numpy/jax already in the tree)
and degrades to zero overhead when disabled.

``guards`` re-exports are lazy (PEP 562): the CPU-only consumers of this
package — downloads, native compiles, HH-suite featurization workers —
must not drag jax/optax (multi-second imports that can claim accelerator
devices) into processes that never train.
"""

from deepinteract_tpu.robustness.artifacts import (  # noqa: F401
    ArtifactError,
    CorruptArtifact,
    StaleArtifact,
)
from deepinteract_tpu.robustness.preemption import (  # noqa: F401
    PreemptionGuard,
    TrainingPreempted,
)
from deepinteract_tpu.robustness.retry import retry  # noqa: F401

_GUARD_EXPORTS = ("NonFiniteTrainingError", "apply_guarded_update",
                  "step_is_finite")


def __getattr__(name):
    if name in _GUARD_EXPORTS:
        from deepinteract_tpu.robustness import guards

        return getattr(guards, name)
    raise AttributeError(f"module {__name__!r} has no attribute {name!r}")
