"""Deterministic fault injection for the chaos test suite.

Fault sites are named probe points compiled into the failure-prone layers
(download fetch, native compile, HH-suite invoke, loader batch assembly,
train-step batches). Each site counts its calls; a fault plan maps sites
to the 1-based call numbers that should fail. Plans are exact and
deterministic — no randomness — so every chaos test (and every operator
game-day) reproduces bit-for-bit.

Plan syntax (``DI_FAULTS`` env var or :func:`configure`)::

    site=N          first N calls fault       download.fetch=2
    site=@i,j,k     exactly calls i, j, k     train.nan_batch=@3
    plan;plan;...   multiple sites            download.fetch=2;train.sigterm=@6

Registered sites:

* ``download.fetch``   — raises URLError (transient network failure)
* ``native.compile``   — raises OSError before the compiler subprocess
* ``hhblits.run``      — raises CalledProcessError before hhblits runs
* ``loader.batch``     — raises ValueError while assembling a batch
* ``train.nan_batch``  — poisons every float leaf of the batch with NaN
* ``train.sigterm``    — requests preemption (simulated SIGTERM) at that
  train batch
* ``checkpoint.snapshot`` — raises RESOURCE_EXHAUSTED at the async
  checkpoint's on-device snapshot (the transient second state copy)
* ``serving.admission``  — raises a typed Overloaded at engine submit
* ``serving.assembly``   — raises BatchExecutionError while the flush
  worker featurizes/stacks a coalesced batch
* ``serving.dispatch``   — raises BatchExecutionError at the coalesced
  batch's device dispatch (fails only that group; the worker and the
  engine keep serving — tests/test_serving.py chaos suite)
* ``storage.write``      — raises OSError before an atomic_write opens
  its tmp file (robustness/artifacts.py)
* ``storage.fsync``      — raises OSError after the tmp holds the full
  content but before fsync — the torn-tmp crash point (orphan tmp left,
  destination untouched)
* ``storage.replace``    — raises OSError before the atomic rename
  (complete tmp orphaned, destination still the old version)
* ``storage.read``       — poisons a verified read/verify with a
  CorruptArtifact (simulated on-disk corruption)
* ``checkpoint.restore`` — marks a checkpoint step corrupt at restore
  verification, driving the last-good fallback walk
  (training/checkpoint.py)
* ``fleet.spawn``         — raises OSError before a worker process is
  spawned (serving/fleet.py; exercises the restart backoff path)
* ``fleet.probe``         — raises ConnectionError at a worker health
  probe (a healthy worker looks unreachable to the supervisor)
* ``fleet.kill``          — raises OSError when the supervisor delivers
  a signal to a worker (a drain's SIGTERM fails; the SIGKILL fallback
  must still retire the worker)
* ``fleet.preempt``       — boolean site fired once per supervisor poll
  tick: when it fires, the newest routable worker is preempted (SIGTERM,
  expected capacity loss — no circuit penalty, immediate replacement
  spawn; serving/fleet.py poll_once)
* ``autoscale.decision``  — raises RuntimeError at the moment an
  autoscaler decision would commit (serving/autoscaler.py); the tick
  must swallow it, count it, and leave the fleet unchanged
* ``training.step_crash`` — raises RuntimeError at that train batch
  (hard process crash with a traceback — the training supervisor's
  restart-into---resume path, training/supervisor.py)
* ``training.hang``       — freezes the step loop forever at that train
  batch while the heartbeat thread keeps beating (the wedged-collective
  simulation; only the supervisor watchdog's SIGKILL ends it)
* ``data.place``          — raises at the input pipeline's batch
  placement (data/pipeline.py); the trainer must surface it as a typed
  ``PlacementError`` — even when placement ran on the prefetch thread —
  never hang on a dead queue
* ``data.place_hang``     — freezes batch placement forever (on the
  placement thread under --device_prefetch): the wedged-input-pipeline
  simulation whose stale-progress heartbeat signature the training
  supervisor watchdog SIGKILLs

When no plan is configured every probe is a dict lookup on an empty map —
effectively free on hot paths.
"""

from __future__ import annotations

import os
import threading
from typing import Dict, Optional, Set, Union

from deepinteract_tpu.obs import metrics as obs_metrics

# Chaos-visibility counter: every injected fault is also a telemetry
# event, so a game day (or the chaos suite) can assert the faults it
# configured actually fired — per site, from the same registry /metrics
# serves.
_INJECTED = obs_metrics.counter(
    "di_faults_injected_total", "Faults injected by the active DI_FAULTS plan",
    labelnames=("site",))

_lock = threading.Lock()
_plan: Optional[Dict[str, Set[int]]] = None  # None -> read env lazily
_counts: Dict[str, int] = {}


def _parse(spec: str) -> Dict[str, Set[int]]:
    plan: Dict[str, Set[int]] = {}
    for part in spec.split(";"):
        part = part.strip()
        if not part:
            continue
        site, eq, val = part.partition("=")
        site, val = site.strip(), val.strip()
        if not eq or not site or not val:
            raise ValueError(f"malformed fault spec {part!r} (want site=N "
                             "or site=@i,j,k)")
        if val.startswith("@"):
            plan[site] = {int(v) for v in val[1:].split(",") if v.strip()}
        else:
            plan[site] = set(range(1, int(val) + 1))
    return plan


def configure(plan: Union[str, Dict[str, object], None]) -> None:
    """Install a fault plan. ``str`` uses the ``DI_FAULTS`` syntax; a dict
    maps site -> N (first N calls) or site -> iterable of call numbers;
    ``None`` re-arms lazy loading from the environment."""
    global _plan
    with _lock:
        _counts.clear()
        if plan is None:
            _plan = None
            return
        if isinstance(plan, str):
            _plan = _parse(plan)
            return
        parsed: Dict[str, Set[int]] = {}
        for site, val in plan.items():
            if isinstance(val, int):
                parsed[site] = set(range(1, val + 1))
            else:
                parsed[site] = {int(v) for v in val}
        _plan = parsed


def reset() -> None:
    """Clear the plan and all call counters (test teardown)."""
    global _plan
    with _lock:
        _plan = {}
        _counts.clear()


def _active_plan() -> Dict[str, Set[int]]:
    global _plan
    if _plan is None:
        with _lock:
            if _plan is None:
                try:
                    _plan = _parse(os.environ.get("DI_FAULTS", ""))
                except ValueError as exc:
                    # The lazy env parse runs inside production probe
                    # sites (loader batches, downloads) whose error
                    # handling must see DATA failures, not a config typo
                    # — e.g. the loader's skip budget would misclassify
                    # this as a corrupt batch and silently eat the
                    # budget. Explicit configure() calls still raise.
                    import logging

                    logging.getLogger(__name__).error(
                        "ignoring malformed DI_FAULTS=%r: %s",
                        os.environ.get("DI_FAULTS"), exc)
                    _plan = {}
    return _plan


def fire(site: str) -> bool:
    """Count a call at ``site``; True iff this call is in the plan."""
    plan = _active_plan()
    if not plan:
        return False
    with _lock:
        if site not in plan:
            return False
        _counts[site] = _counts.get(site, 0) + 1
        fired = _counts[site] in plan[site]
    if fired:
        _INJECTED.inc(site=site)
    return fired


def call_count(site: str) -> int:
    with _lock:
        return _counts.get(site, 0)


def maybe_raise(site: str, make_exc) -> None:
    """Raise ``make_exc()`` if ``site`` faults on this call."""
    if fire(site):
        raise make_exc()


def poison_nan(batch):
    """Every float leaf of the pytree replaced with NaN (host-side numpy)
    — the canonical bad-batch injection for the non-finite guard."""
    import jax
    import numpy as np

    def poison(leaf):
        arr = np.asarray(leaf)
        if np.issubdtype(arr.dtype, np.floating):
            return np.full_like(arr, np.nan)
        return leaf

    return jax.tree_util.tree_map(poison, batch)


def maybe_poison(site: str, batch):
    return poison_nan(batch) if fire(site) else batch
