"""Durable artifacts: checksummed persistence, verified reads, quarantine.

Every stateful subsystem here persists something — orbax checkpoints plus
the ``trainer_state.json`` sidecar, the embedding-cache npz spill, screen
manifests, the tuning store, heartbeats, download caches — and a
production deployment on preemptible capacity cannot treat the disk that
holds them as trustworthy: a kill -9 mid-write tears files, a flaky
device flips bits, and a torn ``last/`` checkpoint used to block
``--resume`` outright. This module is the single integrity layer they
all write through:

* :func:`atomic_write` — tmp + flush + fsync + ``os.replace`` + directory
  fsync. A reader never observes a torn file; a crash leaves at worst an
  orphaned ``*.tmp`` (cleaned by :func:`sweep_tmp` / ``cli/fsck.py``),
  never a half-written destination.
* **Integrity sidecars** — ``<name>.integrity.json`` records the SHA-256,
  byte length, and schema kind/version of the artifact (plus caller
  extras such as ``weights_signature``). :func:`verify_file` /
  :func:`verify_read` check bytes-on-disk against the sidecar before any
  deserializer runs, raising typed :class:`CorruptArtifact` /
  :class:`StaleArtifact` instead of feeding garbage downstream.
* :func:`quarantine` — a corrupt artifact is moved aside as
  ``<name>.corrupt-<ts>`` (sidecar too), counted in
  ``di_artifact_corrupt_total{kind}``, and logged with one reason line,
  so recovery is automatic AND auditable — never a silent delete.
* :func:`sweep_tmp` — startup sweep of orphaned ``*.tmp`` files from
  killed runs.
* **Directory trees** (orbax checkpoint steps): :func:`write_tree_sidecar`
  / :func:`verify_tree` hash every file under the step directory, so a
  single flipped bit in any payload shard fails verification.

Write-ordering note: the artifact file is replaced first, then its
sidecar. A crash between the two leaves a fresh file with a stale
sidecar — which verification rejects (fail-closed) and the owning
subsystem recovers from (fall back / re-derive), the same path as real
corruption. No ordering can make two files one atom; fail-closed is the
safe half.

Chaos hooks (robustness/faults.py): ``storage.write`` fails before the
tmp is written, ``storage.fsync`` after content is in the tmp (the torn-
tmp crash point), ``storage.replace`` before the rename (complete tmp,
old destination), and ``storage.read`` poisons a verified read — so the
chaos suite can kill every write at every stage and corrupt every read,
deterministically.
"""

from __future__ import annotations

import hashlib
import json
import logging
import os
import time
from typing import Any, Dict, List, Optional, Union

from deepinteract_tpu.obs import metrics as obs_metrics
from deepinteract_tpu.robustness import faults

logger = logging.getLogger(__name__)

SCHEMA = "artifact-integrity/v1"
SIDECAR_SUFFIX = ".integrity.json"
TMP_SUFFIX = ".tmp"

# Schema kind of orbax checkpoint-step tree sidecars. Lives here (not in
# training/checkpoint.py) so file-only consumers — cli/fsck.py — can
# label the same artifact class identically without importing the
# jax/orbax-heavy training stack.
CHECKPOINT_KIND = "orbax-checkpoint"

_CORRUPT = obs_metrics.counter(
    "di_artifact_corrupt_total",
    "Corrupt artifacts detected and quarantined, by schema kind",
    labelnames=("kind",))
_TMP_SWEPT = obs_metrics.counter(
    "di_artifact_tmp_swept_total",
    "Orphaned .tmp files removed by the startup sweep")


class ArtifactError(RuntimeError):
    """Base of typed artifact-integrity failures."""

    def __init__(self, path: str, reason: str):
        super().__init__(f"{path}: {reason}")
        self.path = path
        self.reason = reason


class CorruptArtifact(ArtifactError):
    """Bytes on disk do not match the integrity sidecar (truncation, bit
    flip, torn write, unparseable sidecar). The artifact must not be
    deserialized; quarantine and recover."""


class StaleArtifact(ArtifactError):
    """The artifact is intact but is not the one the reader wants: wrong
    schema kind/version, or an ``expect`` field (e.g. weights_signature)
    disagrees. Never silently reinterpreted."""


# -- hashing ---------------------------------------------------------------


def sha256_file(path: str, chunk: int = 1 << 20) -> str:
    h = hashlib.sha256()
    with open(path, "rb") as f:
        while True:
            b = f.read(chunk)
            if not b:
                break
            h.update(b)
    return h.hexdigest()


def sidecar_path(path: str) -> str:
    return path + SIDECAR_SUFFIX


# -- atomic writes ---------------------------------------------------------


def _fsync_dir(directory: str) -> None:
    """fsync the containing directory so the rename itself is durable
    (POSIX: a crash after replace but before the dir sync can otherwise
    forget the new directory entry)."""
    fd = os.open(directory or ".", os.O_RDONLY)
    try:
        os.fsync(fd)
    finally:
        os.close(fd)


def atomic_write(path: str, data: Union[bytes, str], *,
                 fsync: bool = True) -> None:
    """Write ``data`` to ``path`` so a reader sees the old content or the
    new content, never a mixture — and, with ``fsync`` (default), so the
    new content survives power loss once this returns.

    A failure mid-sequence may leave an orphaned ``<path>.<pid>.tmp``
    (exactly what a kill -9 leaves); it is NOT cleaned up here so the
    fault-injected paths model the crash faithfully — :func:`sweep_tmp`
    owns orphan cleanup. ``fsync=False`` is for freshness files
    (heartbeats) whose value is atomicity, not durability.
    """
    if isinstance(data, str):
        data = data.encode("utf-8")
    faults.maybe_raise(
        "storage.write", lambda: OSError("injected storage.write fault"))
    directory = os.path.dirname(os.path.abspath(path))
    os.makedirs(directory, exist_ok=True)
    tmp = f"{path}.{os.getpid()}{TMP_SUFFIX}"
    with open(tmp, "wb") as f:
        f.write(data)
        f.flush()
        faults.maybe_raise(
            "storage.fsync", lambda: OSError("injected storage.fsync fault"))
        if fsync:
            os.fsync(f.fileno())
    faults.maybe_raise(
        "storage.replace", lambda: OSError("injected storage.replace fault"))
    os.replace(tmp, path)
    if fsync:
        _fsync_dir(directory)


def _write_sidecar_from(path: str, kind: str, version: int,
                        extra: Optional[Dict[str, Any]],
                        digest: str, nbytes: int) -> Dict[str, Any]:
    manifest: Dict[str, Any] = {
        "schema": SCHEMA,
        "kind": kind,
        "version": int(version),
        "sha256": digest,
        "bytes": int(nbytes),
        "written_at": time.time(),
    }
    if extra:
        manifest["extra"] = dict(extra)
    atomic_write(sidecar_path(path), json.dumps(manifest, sort_keys=True))
    return manifest


def write_sidecar(path: str, kind: str, version: int = 1,
                  extra: Optional[Dict[str, Any]] = None) -> Dict[str, Any]:
    """Stream-hash an EXISTING file and write its integrity sidecar
    (adopting artifacts not written by this process — downloads, legacy
    files). Returns the manifest dict."""
    return _write_sidecar_from(path, kind, version, extra,
                               sha256_file(path), os.path.getsize(path))


def atomic_write_artifact(path: str, data: Union[bytes, str], kind: str,
                          version: int = 1,
                          extra: Optional[Dict[str, Any]] = None) -> None:
    """:func:`atomic_write` + integrity sidecar — the standard way to
    persist a verifiable single-file artifact. The sidecar hash is
    computed from the in-memory bytes, not a re-read of the file, so a
    durable write costs one write pass, not two I/O passes."""
    if isinstance(data, str):
        data = data.encode("utf-8")
    atomic_write(path, data)
    _write_sidecar_from(path, kind, version, extra,
                        hashlib.sha256(data).hexdigest(), len(data))


# -- verified reads --------------------------------------------------------


def read_sidecar(path: str) -> Optional[Dict[str, Any]]:
    """The parsed sidecar for ``path``, None when absent, and
    :class:`CorruptArtifact` when present but unreadable (a truncated
    sidecar is corruption of the artifact pair, not a missing one)."""
    sc = sidecar_path(path)
    if not os.path.exists(sc):
        return None
    try:
        with open(sc, encoding="utf-8") as f:
            manifest = json.load(f)
    except (OSError, ValueError) as exc:
        raise CorruptArtifact(path, f"unreadable integrity sidecar: {exc}")
    if not isinstance(manifest, dict) or manifest.get("schema") != SCHEMA:
        found = manifest.get("schema") if isinstance(manifest, dict) else type(manifest).__name__
        raise CorruptArtifact(path, f"sidecar schema {found!r} != {SCHEMA}")
    return manifest


def _check_manifest(path: str, manifest: Dict[str, Any],
                    kind: Optional[str], expect: Optional[Dict[str, Any]],
                    size: int, digest: str) -> None:
    """The shared identity + integrity checks behind verify_file /
    verify_read / verify_tree entries."""
    if kind is not None and manifest.get("kind") != kind:
        raise StaleArtifact(
            path, f"kind {manifest.get('kind')!r} != expected {kind!r}")
    for key, want in (expect or {}).items():
        got = (manifest.get("extra") or {}).get(key)
        if got != want:
            raise StaleArtifact(path, f"{key} {got!r} != expected {want!r}")
    if size != manifest.get("bytes"):
        raise CorruptArtifact(
            path, f"truncated: {size} bytes on disk, sidecar recorded "
                  f"{manifest.get('bytes')}")
    if digest != manifest.get("sha256"):
        raise CorruptArtifact(
            path, f"sha256 mismatch: {digest[:12]}… on disk, sidecar "
                  f"recorded {str(manifest.get('sha256'))[:12]}…")


def verify_file(path: str, kind: Optional[str] = None, *,
                require_sidecar: bool = True,
                expect: Optional[Dict[str, Any]] = None,
                ) -> Optional[Dict[str, Any]]:
    """Check ``path`` against its integrity sidecar without reading it
    into memory (streamed hash — right for large files the caller won't
    load, e.g. downloads). Returns the manifest, or None when no sidecar
    exists and ``require_sidecar`` is False (legacy artifact: caller
    proceeds unverified).

    Raises FileNotFoundError (no such artifact), :class:`CorruptArtifact`
    (missing required sidecar, byte-length mismatch = truncation, hash
    mismatch = bit flip/torn write, unreadable sidecar), or
    :class:`StaleArtifact` (kind or ``expect`` mismatch — e.g. a spill
    written under different weights).
    """
    if not os.path.exists(path):
        raise FileNotFoundError(path)
    if faults.fire("storage.read"):
        raise CorruptArtifact(path, "injected storage.read corruption")
    manifest = read_sidecar(path)
    if manifest is None:
        if require_sidecar:
            raise CorruptArtifact(path, "integrity sidecar missing")
        return None
    _check_manifest(path, manifest, kind, expect,
                    os.path.getsize(path), sha256_file(path))
    return manifest


def verify_read(path: str, kind: Optional[str] = None, *,
                require_sidecar: bool = True,
                expect: Optional[Dict[str, Any]] = None) -> bytes:
    """Read the artifact's bytes ONCE and verify that exact buffer
    against the sidecar (hash computed in memory — no second I/O pass,
    and no verify-then-reread window: the bytes returned are the bytes
    checked)."""
    if not os.path.exists(path):
        raise FileNotFoundError(path)
    if faults.fire("storage.read"):
        raise CorruptArtifact(path, "injected storage.read corruption")
    manifest = read_sidecar(path)
    with open(path, "rb") as f:
        data = f.read()
    if manifest is None:
        if require_sidecar:
            raise CorruptArtifact(path, "integrity sidecar missing")
        return data
    _check_manifest(path, manifest, kind, expect,
                    len(data), hashlib.sha256(data).hexdigest())
    return data


def verify_json(path: str, kind: Optional[str] = None, *,
                require_sidecar: bool = True,
                expect: Optional[Dict[str, Any]] = None) -> Any:
    """Verified read + JSON decode. A decode failure after a passing
    hash check means the WRITER persisted garbage — still surfaced as
    :class:`CorruptArtifact` so every caller has one error to handle."""
    raw = verify_read(path, kind, require_sidecar=require_sidecar,
                      expect=expect)
    try:
        return json.loads(raw.decode("utf-8"))
    except (UnicodeDecodeError, ValueError) as exc:
        raise CorruptArtifact(path, f"verified bytes are not JSON: {exc}")


# -- directory trees (orbax checkpoint steps) ------------------------------


def _tree_files(dir_path: str) -> Dict[str, str]:
    out = {}
    for root, _dirs, files in os.walk(dir_path):
        for name in files:
            p = os.path.join(root, name)
            out[os.path.relpath(p, dir_path).replace(os.sep, "/")] = p
    return out


def write_tree_sidecar(dir_path: str, kind: str, version: int = 1,
                       extra: Optional[Dict[str, Any]] = None,
                       ) -> Dict[str, Any]:
    """Integrity sidecar for a DIRECTORY artifact (an orbax step dir):
    per-file sha256 + byte length for every file under it, written next
    to the directory as ``<dir>.integrity.json``."""
    files = {
        rel: {"sha256": sha256_file(p), "bytes": os.path.getsize(p)}
        for rel, p in sorted(_tree_files(dir_path).items())
    }
    manifest: Dict[str, Any] = {
        "schema": SCHEMA,
        "kind": kind,
        "version": int(version),
        "tree": True,
        "files": files,
        "bytes": sum(e["bytes"] for e in files.values()),
        "written_at": time.time(),
    }
    if extra:
        manifest["extra"] = dict(extra)
    atomic_write(sidecar_path(dir_path), json.dumps(manifest, sort_keys=True))
    return manifest


def verify_tree(dir_path: str, kind: Optional[str] = None, *,
                require_sidecar: bool = True,
                ) -> Optional[Dict[str, Any]]:
    """Verify every file of a directory artifact against its tree
    sidecar. Missing, truncated, altered, AND unexpected-extra files all
    raise :class:`CorruptArtifact` — a finalized checkpoint step never
    legitimately changes shape after its sidecar is written."""
    if not os.path.isdir(dir_path):
        raise FileNotFoundError(dir_path)
    if faults.fire("storage.read"):
        raise CorruptArtifact(dir_path, "injected storage.read corruption")
    manifest = read_sidecar(dir_path)
    if manifest is None:
        if require_sidecar:
            raise CorruptArtifact(dir_path, "integrity sidecar missing")
        return None
    if kind is not None and manifest.get("kind") != kind:
        raise StaleArtifact(
            dir_path, f"kind {manifest.get('kind')!r} != expected {kind!r}")
    recorded = manifest.get("files")
    if not isinstance(recorded, dict):
        raise CorruptArtifact(dir_path, "sidecar carries no file map")
    on_disk = _tree_files(dir_path)
    missing = sorted(set(recorded) - set(on_disk))
    if missing:
        raise CorruptArtifact(
            dir_path, f"{len(missing)} recorded file(s) missing "
                      f"(first: {missing[0]})")
    extra_files = sorted(set(on_disk) - set(recorded))
    if extra_files:
        raise CorruptArtifact(
            dir_path, f"{len(extra_files)} file(s) not in the sidecar "
                      f"(first: {extra_files[0]}) — partial overwrite?")
    for rel, entry in recorded.items():
        p = on_disk[rel]
        size = os.path.getsize(p)
        if size != entry.get("bytes"):
            raise CorruptArtifact(
                dir_path, f"{rel}: truncated ({size} bytes vs recorded "
                          f"{entry.get('bytes')})")
        if sha256_file(p) != entry.get("sha256"):
            raise CorruptArtifact(dir_path, f"{rel}: sha256 mismatch")
    return manifest


# -- quarantine + sweep ----------------------------------------------------


def quarantine(path: str, kind: str, reason: str) -> Optional[str]:
    """Move a corrupt artifact (file or directory) and its sidecar aside
    as ``<name>.corrupt-<ts>``, count it, and log the one reason line.
    Returns the quarantine path, or None when the move itself failed
    (full disk/permissions — the corruption is still counted+logged)."""
    ts = int(time.time())
    dest = f"{path}.corrupt-{ts}"
    n = 0
    while os.path.exists(dest):
        n += 1
        dest = f"{path}.corrupt-{ts}.{n}"
    _CORRUPT.inc(kind=kind)
    try:
        os.replace(path, dest)
    except OSError as exc:
        logger.error("corrupt artifact %s (%s): %s — quarantine move "
                     "FAILED: %s", path, kind, reason, exc)
        return None
    sc = sidecar_path(path)
    if os.path.exists(sc):
        try:
            os.replace(sc, sidecar_path(dest))
        except OSError:  # the payload is already aside; sidecar orphan
            pass  # is cleaned by fsck
    logger.error("corrupt artifact %s (%s): %s — quarantined to %s",
                 path, kind, reason, dest)
    return dest


def sweep_tmp(directory: str, prefix: str = "",
              contains: str = "") -> List[str]:
    """Remove orphaned ``*.tmp`` files left by killed writers, directly
    under ``directory`` (non-recursive — each subsystem sweeps its own
    root at startup, when none of ITS writers can be mid-flight).
    ``prefix`` (basename start) and ``contains`` (substring, e.g.
    ``".integrity.json."``) restrict the sweep to tmps this subsystem
    owns, so one sharing a directory never reaps a neighbor's live
    write. Returns the removed paths; never raises on per-file errors."""
    removed: List[str] = []
    try:
        names = os.listdir(directory)
    except OSError:
        return removed
    for name in names:
        if not name.endswith(TMP_SUFFIX):
            continue
        if prefix and not name.startswith(prefix):
            continue
        if contains and contains not in name:
            continue
        p = os.path.join(directory, name)
        if not os.path.isfile(p):
            continue
        try:
            os.unlink(p)
        except OSError:
            continue
        _TMP_SWEPT.inc()
        removed.append(p)
    if removed:
        logger.warning("swept %d orphaned tmp file(s) under %s",
                       len(removed), directory)
    return removed
