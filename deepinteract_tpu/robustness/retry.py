"""Exponential backoff with jitter + deadline for flaky side effects.

The feature toolchain this repo inherits from DeepInteract leans on
external moving parts — Zenodo downloads, a system C++ compiler, the
HH-suite binaries, shared filesystems — all of which fail transiently in
ways a blind immediate retry either misses (rate limits) or makes worse
(thundering herd on a shared NFS). One decorator centralizes the policy:

* exponential backoff (``base_delay * 2**attempt``) capped at
  ``max_delay``, with full jitter (uniform in ``[delay/2, delay]``) so
  concurrent workers decorrelate;
* an overall ``deadline`` in seconds — a retry loop must never outlive
  the grace period of the job around it;
* a ``retryable`` predicate for exception-level triage (e.g. HTTP 4xx is
  permanent, 5xx/connection-reset is transient);
* the ORIGINAL exception is re-raised on exhaustion — callers' error
  handling and the chaos suite's "permanent failures still hard-fail
  with the original error" criterion both depend on that.

Env knobs (read at call time so tests and operators can adjust without
code changes): ``DI_RETRY_MAX_ATTEMPTS``, ``DI_RETRY_BASE_DELAY``,
``DI_RETRY_MAX_DELAY``, ``DI_RETRY_DEADLINE`` override whatever the call
site configured.
"""

from __future__ import annotations

import functools
import logging
import os
import random
import time
from typing import Callable, Optional, Tuple, Type

from deepinteract_tpu.obs import metrics as obs_metrics

logger = logging.getLogger(__name__)

# One series per retry site (the decorator's label), counting FAILED
# attempts that led to another try — a sustained nonzero rate here is the
# earliest external-dependency degradation signal the process has.
_RETRY_ATTEMPTS = obs_metrics.counter(
    "di_retry_attempts_total",
    "Failed attempts that were retried, per retry-decorated site",
    labelnames=("site",))

_ENV_OVERRIDES = {
    "max_attempts": ("DI_RETRY_MAX_ATTEMPTS", int),
    "base_delay": ("DI_RETRY_BASE_DELAY", float),
    "max_delay": ("DI_RETRY_MAX_DELAY", float),
    "deadline": ("DI_RETRY_DEADLINE", float),
}


def _effective(name: str, value):
    env_name, cast = _ENV_OVERRIDES[name]
    raw = os.environ.get(env_name)
    if raw is None:
        return value
    try:
        return cast(raw)
    except ValueError:
        logger.warning("ignoring malformed %s=%r", env_name, raw)
        return value


def compute_delay(attempt: int, base_delay: float, max_delay: float,
                  rng: Optional[random.Random] = None) -> float:
    """Backoff for the given 0-based failed-attempt index, with full
    jitter in [delay/2, delay] (decorrelates concurrent retriers)."""
    delay = min(max_delay, base_delay * (2.0 ** attempt))
    r = rng.random() if rng is not None else random.random()
    return delay * (0.5 + 0.5 * r)


def retry(
    exceptions: Tuple[Type[BaseException], ...] = (Exception,),
    max_attempts: int = 3,
    base_delay: float = 0.5,
    max_delay: float = 30.0,
    deadline: Optional[float] = None,
    retryable: Optional[Callable[[BaseException], bool]] = None,
    sleep: Callable[[float], None] = time.sleep,
    clock: Callable[[], float] = time.monotonic,
    rng: Optional[random.Random] = None,
    label: Optional[str] = None,
) -> Callable:
    """Decorator: retry the wrapped callable on transient failures.

    ``exceptions`` gates which exception TYPES are candidates;
    ``retryable(exc)`` (optional) refines per-instance. Anything else —
    and the final failed attempt — propagates unchanged. ``sleep`` /
    ``clock`` / ``rng`` are injectable for deterministic tests.
    """

    def decorate(fn: Callable) -> Callable:
        name = label or getattr(fn, "__qualname__", repr(fn))

        @functools.wraps(fn)
        def wrapper(*args, **kwargs):
            attempts = max(1, _effective("max_attempts", max_attempts))
            base = _effective("base_delay", base_delay)
            cap = _effective("max_delay", max_delay)
            limit = _effective("deadline", deadline)
            start = clock()
            for attempt in range(attempts):
                try:
                    return fn(*args, **kwargs)
                except exceptions as exc:
                    if retryable is not None and not retryable(exc):
                        raise
                    if attempt + 1 >= attempts:
                        raise
                    pause = compute_delay(attempt, base, cap, rng)
                    if limit is not None and (clock() - start) + pause > limit:
                        logger.warning(
                            "%s: retry deadline (%.1fs) exhausted after "
                            "attempt %d: %s", name, limit, attempt + 1, exc)
                        raise
                    _RETRY_ATTEMPTS.inc(site=name)
                    logger.warning(
                        "%s: attempt %d/%d failed (%s); retrying in %.2fs",
                        name, attempt + 1, attempts, exc, pause)
                    sleep(pause)
            raise AssertionError("unreachable")  # pragma: no cover

        return wrapper

    return decorate
