"""Preemption-safe training: catch SIGTERM/SIGINT, flush, resume.

TPU pools (and most batch schedulers) preempt with SIGTERM plus a short
grace period. Without handling, the process dies mid-epoch and the run
loses everything since the last manual checkpoint. With
:class:`PreemptionGuard` installed around ``Trainer.fit``:

* the first SIGTERM/SIGINT sets a flag — no exception is thrown from the
  (async-unsafe) signal context;
* the training loop polls the flag at dispatch and epoch boundaries and
  raises :class:`TrainingPreempted` at the next safe point;
* ``fit`` unwinds through its save-drain ``finally``, so the ``last/``
  orbax checkpoint of the most recent epoch boundary is fully flushed to
  disk before the process exits;
* a rerun with ``--resume`` restores the step counter, optimizer state
  and EarlyStopping/best-k bookkeeping and reproduces the uninterrupted
  run exactly (training is epoch-deterministic: data order, dropout folds
  and optimizer math are all keyed on the restored state).

Checkpoint granularity is the epoch boundary: a preemption mid-epoch
discards that epoch's partial updates rather than persisting a state the
uninterrupted run never visits — the property the resume-equivalence
chaos test pins down.

A second signal bypasses the guard (restores the previous handler and
re-delivers), so a hung flush can still be killed interactively.
"""

from __future__ import annotations

import logging
import signal
import threading
from typing import Optional

logger = logging.getLogger(__name__)

_SIGNALS = (signal.SIGTERM, signal.SIGINT)


class TrainingPreempted(RuntimeError):
    """Raised at a safe point after a preemption request; the ``last/``
    checkpoint has been (or is being, and will be drained) flushed."""


class PreemptionGuard:
    """Context manager installing cooperative SIGTERM/SIGINT handlers.

    Usage::

        with PreemptionGuard() as guard:
            ...  # poll guard.requested at safe points

    Handlers are installed only in the main thread (CPython restriction);
    elsewhere the guard degrades to a poll-only flag that fault injection
    or the host application can still :meth:`request`.
    """

    def __init__(self, log=logger.warning):
        self._event = threading.Event()
        self._reason: Optional[str] = None
        self._previous = {}
        self._log = log
        self._logged = True  # nothing pending to announce yet

    # -- flag ------------------------------------------------------------

    @property
    def requested(self) -> bool:
        return self._event.is_set()

    @property
    def reason(self) -> Optional[str]:
        return self._reason

    def request(self, reason: str = "preemption requested") -> None:
        """Ask the training loop to stop at the next safe point. Safe to
        call from other threads or fault injection (logs immediately —
        the signal handler sets the flag directly instead, deferring the
        log to :meth:`check` to stay async-signal-safe)."""
        if not self._event.is_set():
            self._reason = reason
            self._event.set()
            self._logged = True
            self._log(f"preemption: {reason}; will checkpoint and exit at "
                      "the next safe point")

    def check(self) -> None:
        """Raise :class:`TrainingPreempted` if a stop was requested."""
        if self._event.is_set():
            if not self._logged:
                self._logged = True
                self._log(f"preemption: {self._reason}; will checkpoint "
                          "and exit at the next safe point")
            raise TrainingPreempted(self._reason or "preempted")

    # -- signal plumbing -------------------------------------------------

    def _handler(self, signum, frame):
        if self._event.is_set():
            # Second signal: the operator means it. Re-deliver through the
            # previous handler (usually the default: terminate). A None
            # previous handler (installed at the C level — getsignal
            # cannot represent it) degrades to SIG_DFL.
            prev = self._previous.get(signum) or signal.SIG_DFL
            signal.signal(signum, prev)
            signal.raise_signal(signum)
            return
        # Flag only — NO logging from signal context: the interrupted main
        # thread may be mid-write on the same buffered stream, and a
        # reentrant print raises RuntimeError, turning a clean preemption
        # into a crash. The polling site (check) emits the log line.
        self._reason = f"received {signal.Signals(signum).name}"
        self._event.set()
        self._logged = False

    def __enter__(self) -> "PreemptionGuard":
        try:
            for sig in _SIGNALS:
                self._previous[sig] = signal.signal(sig, self._handler)
        except ValueError:
            # Not the main thread: signals cannot be hooked here; the
            # flag-based protocol (request/check) still works.
            self._previous = {}
            logger.debug("PreemptionGuard: not in main thread; signal "
                         "handlers not installed (flag-only mode)")
        return self

    def __exit__(self, exc_type, exc, tb) -> None:
        for sig, prev in self._previous.items():
            try:
                # None = a C-level handler signal.signal cannot restore;
                # SIG_DFL is the only faithful-enough fallback.
                signal.signal(sig, prev if prev is not None else signal.SIG_DFL)
            except ValueError:  # pragma: no cover - thread teardown races
                pass
        self._previous = {}
