"""On-device non-finite step guard.

One NaN loss or gradient silently poisons every parameter on the next
optimizer update, and the run limps along producing garbage until a human
notices. The guard makes the failure mode explicit and recoverable:

* :func:`step_is_finite` — a single fused reduction (``isfinite(loss) &
  isfinite(global_norm(grads))``) that is true iff the step is safe to
  apply. It runs on device inside the jitted step; no host sync.
* :func:`apply_guarded_update` — ``lax.cond`` between the normal
  ``apply_gradients`` and a skip that leaves params/opt-state/step/
  batch-stats untouched and increments ``TrainState.bad_steps`` (the
  consecutive-skip counter; any good step resets it to zero).
* The Trainer reads the counter from the step metrics and aborts with a
  diagnostic dump (:func:`dump_diagnostics`) once it reaches
  ``LoopConfig.max_bad_steps`` — a stream of consecutive non-finite steps
  means the run is unrecoverable (bad data shard, diverged optimizer),
  not transient.

Multi-host agreement: the guard decision is computed from the
psum/pmean-averaged loss and gradients (or their GSPMD-replicated
equivalents), which are bitwise identical on every host — so every host
takes the same ``lax.cond`` branch and the same abort decision by
construction. The Trainer additionally cross-checks the counter with
``parallel.multihost.assert_same_across_hosts`` before aborting, because
a divergent abort would strand the surviving hosts in a collective.
"""

from __future__ import annotations

import json
import os
from typing import Any, Dict, Tuple

import jax
import jax.numpy as jnp
import numpy as np
import optax


class NonFiniteTrainingError(RuntimeError):
    """Raised by the Trainer after ``max_bad_steps`` consecutive skipped
    (non-finite) optimizer steps. Carries the diagnostics file path."""

    def __init__(self, message: str, diagnostics_path: str | None = None):
        super().__init__(message)
        self.diagnostics_path = diagnostics_path


def step_is_finite(loss: jnp.ndarray, grads: Any) -> jnp.ndarray:
    """Scalar bool: True iff ``loss`` and every gradient entry are finite.

    ``global_norm`` folds the whole gradient tree into one scalar whose
    finiteness is equivalent to all-entries-finite (any NaN/inf propagates
    through the sum of squares), so the check costs one reduction instead
    of a per-leaf ``jnp.isfinite().all()`` sweep.
    """
    return jnp.isfinite(loss) & jnp.isfinite(optax.global_norm(grads))


def apply_guarded_update(state, grads, loss, batch_stats) -> Tuple[Any, jnp.ndarray]:
    """Apply the optimizer update only when the step is finite.

    Returns ``(new_state, finite)``. On a bad step the state is unchanged
    except ``bad_steps + 1`` — params, opt_state, the step counter, the
    dropout rng fold, and batch statistics (which a NaN batch may also
    have poisoned) all stay at their pre-step values. A good step resets
    ``bad_steps`` to zero. Both branches live under ``lax.cond``: the
    decision stays on device and costs no host round trip.
    """
    if state.bad_steps is None:
        raise ValueError(
            "guarded update needs TrainState.bad_steps initialized; build "
            "the state via create_train_state (or pass bad_steps=0)"
        )
    finite = step_is_finite(loss, grads)

    def update(_):
        new = state.apply_gradients(grads=grads, batch_stats=batch_stats)
        return new.replace(bad_steps=jnp.zeros_like(state.bad_steps))

    def skip(_):
        return state.replace(bad_steps=state.bad_steps + 1)

    return jax.lax.cond(finite, update, skip, None), finite


def summarize_batch(batch) -> Dict[str, Any]:
    """Host-side summary of a (host numpy) batch pytree for the diagnostic
    dump: per-leaf shape/dtype plus NaN/inf counts for float leaves and the
    contact-target density — enough to identify a poisoned shard without
    shipping the full arrays."""
    leaves_info = []
    for path, leaf in jax.tree_util.tree_flatten_with_path(batch)[0]:
        arr = np.asarray(leaf)
        info: Dict[str, Any] = {
            "path": jax.tree_util.keystr(path),
            "shape": list(arr.shape),
            "dtype": str(arr.dtype),
        }
        if np.issubdtype(arr.dtype, np.floating):
            info["nan_count"] = int(np.isnan(arr).sum())
            info["inf_count"] = int(np.isinf(arr).sum())
        elif np.issubdtype(arr.dtype, np.integer):
            info["sum"] = int(arr.sum())
        leaves_info.append(info)
    return {"leaves": leaves_info}


def dump_diagnostics(directory: str, payload: Dict[str, Any]) -> str:
    """Write an abort-diagnostics JSON (atomic tmp+rename) and return its
    path. Non-finite floats survive the round trip (json's Infinity/NaN
    literals) — they are the whole point of the dump."""
    os.makedirs(directory or ".", exist_ok=True)
    path = os.path.join(
        directory or ".",
        f"nonfinite_abort_epoch{payload.get('epoch', 'x')}"
        f"_step{payload.get('step', 'x')}.json",
    )
    from deepinteract_tpu.robustness import artifacts

    artifacts.atomic_write(path, json.dumps(payload, indent=2, default=str))
    return path
