"""Benchmark harness: flagship forward + full train step on the live backend.

Contract (driver): prints the headline JSON record —
``{"metric": ..., "value": N, "unit": ..., "vs_baseline": N}`` (plus
compatibility keys; consumers read by name) — on stdout right after the
headline section completes (crash insurance) AND again as the FINAL
terminal line, because the driver parses the last line of its capture
(BENCH_r05.json recorded ``"parsed": null`` when the last line was the
stderr DETAIL dump). ``value`` is the MEDIAN differenced scan sample; the
min sample is a supplementary key only (its r5 headline role was
optimistically biased by up to the 10% admission band). All detail
(per-bucket timings, min/median variance, compile times, analytic +
cost-model FLOPs, MFU) goes to stderr as a JSON object, so it lands in
BENCH_r{N}.json's tail too.

The reference repo publishes no throughput numbers (BASELINE.md: "Throughput
/ latency numbers: none recorded anywhere in repo"), so ``vs_baseline`` is
the ratio against the north-star proxy from BASELINE.json — the same model's
measured single-process CPU throughput (the "CPU/DGL path" stand-in; target
is >=8x). The CPU number is pinned below from a one-time measurement on this
image (see CPU_BASELINE_COMPLEXES_PER_SEC) rather than re-measured each run:
CPU XLA compilation alone costs minutes and the driver runs this file on a
wall-clock budget.

Model: reference-default flagship — 2 Geometric Transformer layers, 128
hidden, 4 heads, kNN=20, 14-chunk dilated SE-ResNet decoder
(project/utils/deepinteract_utils.py:1012-1019).

MFU: two figures per bucket. ``analytic_mfu`` divides a hand-derived matmul
/conv FLOP count (``analytic_forward_flops``; backward = 2x forward, remat
adds one decoder recompute) by the device's peak — it is <= 1 by
construction and is the number to trust. ``xla_mfu`` uses
``compiled.cost_analysis()['flops']``, which over-counts under
rematerialization/fusion (r2 recorded 2.4 "MFU"); it is kept only as a
cross-check and labeled unreliable.
"""

from __future__ import annotations

import json
import os
import sys
import time

import numpy as np

# The measurement core (differenced timing, host materialization, compile
# retry, MFU guard) lives in deepinteract_tpu/tuning/timing.py — SHARED
# with the autotuner so bench and tuner can never disagree on how time is
# measured. The module imports no jax at import time, so bench's child
# processes stay as light as before.
from deepinteract_tpu.tuning.timing import (  # noqa: E402
    PEAK_FLOPS_BY_KIND,  # noqa: F401  (re-exported for tools/)
    is_transient_compile_error as _is_transient,
    materialize as _materialize,  # noqa: F401  (re-exported for tools/)
    mfu_guard_violations,
    resolve_peak_flops,
    time_compiled as _time_compiled_core,
)

# One-time measurement of the jitted flagship *train step* on this image's CPU
# backend (batch 1, 128-pad, single process): see BENCH_NOTES in git history.
CPU_BASELINE_COMPLEXES_PER_SEC = float(
    os.environ.get("DI_CPU_BASELINE_CPS", "2.23")
)

PEAK_FLOPS = 197e12  # replaced in main() via resolve_peak_flops()

WARMUP = 2
ITERS = int(os.environ.get("DI_BENCH_ITERS", "12"))
REPS = int(os.environ.get("DI_BENCH_REPS", "3"))  # variance: min/median over reps

# Total wall budget for the default section list. The driver runs bench.py
# under its own (larger) timeout; rounds 2-4 proved the r4 section list
# cannot finish inside it (BENCH_r{2,3,4}.json rc=124). The bench now
# self-limits: sections that do not fit the remaining budget are recorded
# as explicit ``skipped`` entries and the process exits rc=0 with a
# complete-by-construction artifact.
BUDGET_S = float(os.environ.get("DI_BENCH_BUDGET", "1620"))
_T0 = time.monotonic()

# Nominal per-section wall estimates (init + compiles + timing + process
# startup), from r5 rehearsal runs on a healthy tunnel AFTER the jitted
# init and device-resident arg reuse; the skip rule adds slack.
SECTION_EST_S = {
    "b1_p128": 440,
    "b8_p128_bf16": 300,
    "b8_p128_remat": 280,
    "b1_p256": 300,
    "eval_path": 220,
    "b1_p384_tiled_fwd": 300,
    "b16_p128_remat": 330,
    "ab_p128": 260,
    "ab_p256": 420,
    "tuned_ab": 320,
    "stem_ab": 260,
    "precision_ab": 300,
    "b1_p384_tiled": 420,
    "b1_p512_tiled": 480,
    "b1_p128_deeplab": 300,
    # +~110s for the ISSUE-17 indexed subsection (1k-chain build + 3
    # funnel queries at top_m=8, CPU rehearsal numbers).
    "screening": 420,
    # k=6 assembly through the real AssemblyRunner: 15 pairs warm +
    # 15 measured, decode-dominated (CPU rehearsal ~1.8s/pair flagship).
    "assembly": 240,
    "input_pipeline": 420,
    "saturation": 240,
    # Two mesh engines + two single engines (compact model): the p512
    # tiled compiles dominate the CPU-rehearsal wall time.
    "mesh_serving": 420,
    "rollover": 180,
    "elasticity": 200,
    "recovery": 240,
    "attribution": 240,
}

# NOTE: do NOT enable JAX_COMPILATION_CACHE_DIR here — executable
# serialization hangs through the axon PJRT tunnel (observed: forward
# compile 40s without the cache, >9 min stuck with it).


def _log(msg: str) -> None:
    print(msg, file=sys.stderr, flush=True)


# ---------------------------------------------------------------------------
# Analytic FLOPs (matmul/conv MACs x2; elementwise ignored — it is <1% here)
# ---------------------------------------------------------------------------


def analytic_forward_flops(batch: int, pad: int, knn: int = 20,
                           hidden: int = 128, geo: int = 2,
                           num_layers: int = 2, chunks: int = 14,
                           dec_ch: int = 128, node_in: int = 113) -> dict:
    """Hand-derived forward FLOPs for the flagship model at one bucket.

    Derivation (MACs per element; FLOPs = 2*MACs):
      * GT per-edge work dominates the graph side (E = N*knn edges/chain):
        init-edge gated Linears (~129k MACs/edge at C=128), conformation
        module (~358k/edge/layer: 2G-neighborhood Linear 2G*C^2, embeds,
        4 ResBlock Linears x3, gates), MHA edge projection + O_edge +
        edge-MLP (~97k/edge/layer), node-side Q/K/V/O/MLP (~130k/node).
      * Decoder per-pixel work dominates overall (P = N^2 pixels):
        1x1 256->128 conv, 56 base + 6 phase2 bottleneck blocks
        (1x1 C->C/2, 3x3 C/2->C/2 = 9*(C/2)^2, 1x1 C/2->C), 2 init
        projections, 2-class head  ->  ~3.35M MACs/pixel at C=128.
    """
    C = hidden
    n = pad
    e = n * knn  # edges per chain
    # --- per chain ---
    embed = n * node_in * C
    init_edge = e * (2 * 28 * C + 7 * C * C + C * 28 + 28 * C)
    conf_edge = (
        2 * geo * C * C          # nbr_linear over the 2G neighborhood
        + (18 * 8 + 8 * C)       # dist embed
        + 2 * geo * C * 64       # downward projection of the neighborhood
        + (3 * 8 + 4 * 8 + 1 * 8 + 3 * 8 * 64)  # dir/orient/amide embeds
        + 64 * C                 # upward projection
        + C * C                  # orig_msg_linear
        + 4 * 3 * C * C          # 4 ResBlocks x 3 Linears
        + C * C                  # res_connect
        + 26 * C + C * C         # final gates + final_linear
    )
    mha_edge = C * C + 2 * C + C * C + 2 * C * 2 * C   # proj_e, softmax, O_e, eMLP
    mha_node = 3 * C * C + C * C + 2 * C * 2 * C       # QKV, O_node, nMLP
    per_layer = e * (conf_edge + mha_edge) + n * mha_node
    # final layer skips O_edge/edge-MLP; counted fully — <2% overestimate
    chain = embed + init_edge + num_layers * per_layer
    # --- decoder ---
    p = n * n
    block = dec_ch * (dec_ch // 2) + 9 * (dec_ch // 2) ** 2 + (dec_ch // 2) * dec_ch
    n_blocks = 4 * chunks + 4 + 2  # base chunks*4 + phase2 (4 + 2 extra)
    decoder_px = (2 * C * dec_ch          # conv2d_1 (256->128)
                  + n_blocks * block
                  + 2 * dec_ch * dec_ch   # two init projections
                  + dec_ch * 2)           # class head
    decoder = p * decoder_px
    macs = batch * (2 * chain + decoder)
    return {
        "forward_flops": 2.0 * macs,
        "decoder_fraction": decoder / (2 * chain + decoder),
        "decoder_flops": 2.0 * batch * decoder,
    }


def analytic_train_flops(fwd: dict, remat: bool) -> float:
    """fwd + backward (2x fwd) + one decoder recompute under remat."""
    total = 3.0 * fwd["forward_flops"]
    if remat:
        total += fwd["decoder_flops"]
    return total


# ---------------------------------------------------------------------------
# Timing — shared core in deepinteract_tpu/tuning/timing.py (see import at
# top); this wrapper just binds bench's env-driven defaults and stderr log.
# ---------------------------------------------------------------------------


def _time_compiled(fn, args, iters=None, reps=None):
    """(compile_s, timing dict, xla_flops) under the shared differenced
    protocol (tuning/timing.py:time_compiled — the SAME function the
    autotuner measures with)."""
    return _time_compiled_core(
        fn, args,
        iters=ITERS if iters is None else iters,
        reps=REPS if reps is None else reps,
        warmup=WARMUP, log=_log,
    )


def _make_batch(batch_size, n1, n2, n_pad, knn=20, geo=2, seed=0):
    from deepinteract_tpu.data.graph import stack_complexes
    from deepinteract_tpu.data.synthetic import random_complex

    rng = np.random.default_rng(seed)
    return stack_complexes(
        [
            random_complex(n1, n2, rng=rng, n_pad1=n_pad, n_pad2=n_pad, knn=knn,
                           geo_nbrhd_size=geo)
            for _ in range(batch_size)
        ]
    )


def _dump_json(payload, path) -> None:
    if not path:
        return
    tmp = path + ".tmp"
    with open(tmp, "w") as fh:
        json.dump(payload, fh)
    os.replace(tmp, path)


def _dump_partial(detail) -> None:
    """Persist the child's detail fragment after every sub-measurement, so
    a section timeout or crash still leaves the rows already measured for
    the parent to merge (a whole r4 driver run died with only 2 of 6
    sections landed; partial dumps bound the loss to one sub-measurement)."""
    _dump_json(detail, os.environ.get("DI_BENCH_OUT"))


def _dump_parent(detail) -> None:
    """Parent-side cumulative flush after every merged section (to
    DI_BENCH_DETAIL_OUT when set): a parent killed between sections leaves
    the full merged view of everything finished, not just child
    fragments scattered in temp files."""
    _dump_json(detail, os.environ.get("DI_BENCH_DETAIL_OUT"))


def _crosscheck_mfu(entry, path: str, xla_flops: float,
                    analytic_flops: float) -> None:
    """Record the XLA-vs-analytic FLOP cross-check ratio in the bucket
    entry itself (VERDICT satellite: a repeat of the r2/r3 impossible-MFU
    readings must be flagged in the RECORD, not only by the hard guard's
    raise).

    Interpretation: ``cost_analysis`` counts every op and double-counts
    under remat/fusion, so a healthy ratio is >= ~1 (xla >= analytic,
    elementwise + recompute overhead). A ratio clearly BELOW 1 means the
    hand-derived analytic count exceeds what XLA says the graph computes —
    the analytic MFU is then inflated and untrustworthy, exactly the
    regime that produced the r2/r3 readings."""
    ratio = xla_flops / max(analytic_flops, 1.0)
    entry[f"mfu_crosscheck_ratio_{path}"] = ratio
    if ratio < 0.9:
        entry.setdefault("mfu_crosscheck_flags", []).append(
            f"{path}: xla/analytic FLOP ratio {ratio:.3f} < 0.9 — analytic "
            "FLOPs (and so analytic MFU) overstate this graph")


def bench_bucket(model, state, batch, label, detail, remat, scan_k,
                 guard_mfu=True, mode="full"):
    """Measure one (model, batch) bucket.

    ``mode``: 'full' = forward + per-dispatch train + scanned train (the
    headline bucket); 'lean' = scanned train + forward only — the scan
    figure is the decision-grade one (single-dispatch timings carry
    ±10-20% tunnel spread, BASELINE.md) and skipping the per-dispatch
    train step saves its compile (~60-100 s), which is what blew the
    driver's wall budget in r2-r4; 'fwd' = forward only (inference-tier
    buckets, e.g. the tiled long-context shapes whose train-step graphs
    crash the remote compile helper).

    ``guard_mfu=False`` for buckets whose architecture the analytic FLOP
    model does not describe (the DeepLab/tiled extras) — there an
    analytic "MFU" above 1 is an accounting artifact, not a timing bug."""
    import jax

    from deepinteract_tpu.training.steps import (
        multi_train_step,
        stack_microbatches,
        train_step,
    )

    bs = int(batch.graph1.node_feats.shape[0])
    pad = int(batch.graph1.node_feats.shape[1])
    afl = analytic_forward_flops(bs, pad)
    a_train = analytic_train_flops(afl, remat)
    cfg = getattr(model, "cfg", None)
    stem = getattr(cfg, "interaction_stem", "materialized") if cfg else None
    dtype_name = (cfg.decoder.compute_dtype if cfg else None)
    entry = {
        "batch": bs, "pad": pad, "mode": mode,
        "interaction_stem": stem,
        "compute_dtype": dtype_name,
        "analytic_forward_flops": afl["forward_flops"],
        "analytic_train_flops": a_train,
        "decoder_flop_fraction": afl["decoder_fraction"],
    }
    detail["buckets"][label] = entry

    def guard(keys):
        # Hard guard (VERDICT r3 item 1): analytic MFU is <=1 by
        # construction, so >1 can only mean the timing is wrong. Fail the
        # bucket loudly rather than publish an impossible number. The
        # threshold logic is shared with the tuner (tuning/timing.py).
        violations = mfu_guard_violations(entry, keys) if guard_mfu else {}
        if violations:
            detail["buckets"][label] = {
                "error": f"impossible analytic MFU (>1.0), timing "
                         f"untrustworthy: {violations}",
                "rejected_entry": entry,
            }
            _log(json.dumps({label: detail["buckets"][label]}))
            _dump_partial(detail)
            raise RuntimeError(f"impossible MFU for {label}: {violations}")

    # Scanned path FIRST for lean buckets: K steps per dispatch. Host
    # dispatch cost scales with result-buffer count (~25 ms for the
    # 3.4k-leaf state through the TPU tunnel), so the scan amortizes it
    # K-fold — this is the throughput a real training run achieves
    # (Trainer steps_per_dispatch). Guarded separately: a scan-only
    # failure (e.g. K stacked batches overflowing HBM) must not discard
    # the numbers already measured.
    def measure_scan():
        try:
            stacked = stack_microbatches([batch] * scan_k)
            mstep = jax.jit(lambda s, bst: multi_train_step(s, bst))
            mc, mt, _ = _time_compiled(
                mstep, (state, stacked),
                iters=max(ITERS // 4, 3), reps=min(REPS, 3))
        except Exception as exc:
            entry["train_scan_error"] = (
                str(exc).splitlines()[0][:300] if str(exc) else repr(exc))
            _dump_partial(detail)
            return
        entry.update({
            "train_scan_k": scan_k,
            "train_scan_ms_per_step": mt["median"] * 1e3 / scan_k,
            "train_scan_ms_per_step_min": mt["min"] * 1e3 / scan_k,
            "train_scan_complexes_per_sec": bs * scan_k / mt["median"],
            "train_scan_compile_s": mc,
            "analytic_train_scan_mfu":
                scan_k * a_train / mt["median"] / PEAK_FLOPS,
            "scan_timing_protocol": mt,
        })
        guard(("analytic_train_scan_mfu",))
        _dump_partial(detail)

    def measure_forward():
        fwd = jax.jit(
            lambda params, bstats, b: model.apply(
                {"params": params, "batch_stats": bstats},
                b.graph1, b.graph2, train=False,
            )
        )
        fc, ft, fxla = _time_compiled(
            fwd, (state.params, state.batch_stats, batch))
        entry.update({
            "forward_ms": ft["median"] * 1e3,
            "forward_ms_min": ft["min"] * 1e3,
            "forward_compile_s": fc,
            "forward_complexes_per_sec": bs / ft["median"],
            "analytic_forward_mfu":
                afl["forward_flops"] / ft["median"] / PEAK_FLOPS,
            "timing_protocol": ft,
        })
        # Pair-tensor memory accounting: what the materialized [B, L, L,
        # 2C] tensor would cost vs the compiled forward's actual temp
        # (activation) bytes from memory_analysis() — the factorized
        # stem's win, in the record where it can be watched.
        mem = ft.get("memory")
        if cfg is not None:
            from deepinteract_tpu.models.stem import (
                materialized_interaction_bytes,
            )

            dsize = 2 if cfg.decoder.compute_dtype == "bfloat16" else 4
            ib = {"materialized_equiv_bytes": materialized_interaction_bytes(
                bs, pad, pad, cfg.decoder.in_channels, dsize)}
            if mem:
                ib["forward_peak_temp_bytes"] = mem["temp_size_in_bytes"]
            entry["interaction_bytes"] = ib
        if fxla:
            entry["xla_forward_flops"] = fxla
            entry["xla_forward_mfu"] = (fxla / ft["median"]) / PEAK_FLOPS
            _crosscheck_mfu(entry, "forward", fxla, afl["forward_flops"])
        guard(("analytic_forward_mfu",))
        _dump_partial(detail)

    def measure_train():
        tstep = jax.jit(lambda s, b: train_step(s, b))
        tc, tt, txla = _time_compiled(tstep, (state, batch))
        entry.update({
            "train_ms": tt["median"] * 1e3, "train_ms_min": tt["min"] * 1e3,
            "train_compile_s": tc,
            "train_complexes_per_sec": bs / tt["median"],
            "analytic_train_mfu": a_train / tt["median"] / PEAK_FLOPS,
        })
        if txla:
            entry["xla_train_flops"] = txla
            entry["xla_train_mfu"] = (txla / tt["median"]) / PEAK_FLOPS
            _crosscheck_mfu(entry, "train", txla, a_train)
        guard(("analytic_train_mfu",))
        _dump_partial(detail)

    if mode == "fwd":
        measure_forward()
    elif mode == "lean":
        measure_scan()
        measure_forward()
    else:
        measure_forward()
        measure_train()
        measure_scan()
    # Untrustworthy-timing flag (ADVICE r4 item 4): when the MFU guard is
    # off, a noisy rep that hit the 1e-9 clamp (t2 <= t1) or a linearity
    # far from the ideal 2 means the differenced protocol broke for this
    # bucket — flag it instead of publishing a clamped number silently.
    if not guard_mfu:
        for proto_key in ("timing_protocol", "scan_timing_protocol"):
            proto = entry.get(proto_key)
            if proto and (proto["clamped_samples"] > 0
                          or proto["linearity"] < 1.15):
                entry.setdefault("timing_flags", []).append(
                    "untrustworthy: differenced protocol degenerate "
                    f"({proto_key}: clamped={proto['clamped_samples']}, "
                    f"linearity={proto['linearity']:.2f})")
    # Unstable-sample flag (ISSUE-10 satellite): the shared timing core
    # marks protocols whose linearity is outside the healthy band or
    # whose reps disagree (BENCH_r05 shipped headline numbers at
    # linearity 1.53-1.93 without comment). Lift the warning to the
    # entry level so it rides into the section detail — and, for the
    # headline bucket, into the contract line (_build_headline) where
    # tools/check_perf_regression.py widens its tolerance for it.
    for proto_key in ("timing_protocol", "scan_timing_protocol"):
        proto = entry.get(proto_key)
        if proto and proto.get("timing_warning"):
            entry.setdefault("timing_warnings", []).append(
                f"{proto_key}: {proto['timing_warning']}")
    _log(json.dumps({label: entry}))
    _dump_partial(detail)
    return entry


# Shape table: label -> (batch, n1, n2, pad, remat, mode[, dtype]) — the
# optional 7th element overrides the global DI_BENCH_DTYPE for that
# bucket (see b8_p128_bf16). b1_p128 is the
# headline (mode 'full'); b1_p256 is the reference training regime
# (RESIDUE_COUNT_LIMIT = 256, deepinteract_constants.py:10-12); b8/b16
# +remat are the large-batch configs (lean: the scanned figure is the
# decision-grade one and skipping the per-dispatch train compile keeps the
# section inside the driver's wall budget — r2-r4 all rc=124).
BUCKET_SHAPES = {
    "b1_p128": (1, 100, 80, 128, False, "full"),
    "b8_p128_remat": (8, 100, 80, 128, True, "lean"),
    # The throughput/MFU flagship config: bf16 decoder activations at
    # batch 8. The r5 pad-value-tracking decoder removed the float32
    # masking islands that used to neutralize bf16, and the combo now
    # measures 1.58x over f32 at b8 (150 ms/step scanned, 53 c/s,
    # analytic scan MFU ~0.13 — tools/scan_ab.py). Overrides the global
    # DI_BENCH_DTYPE for this bucket only.
    "b8_p128_bf16": (8, 100, 80, 128, True, "lean", "bfloat16"),
    # p256 runs with decoder remat: the scanned decoder's backward stores
    # per-iteration scan residuals, which at 256x256 maps exceed a 16G
    # v5e's HBM without rematerialization (measured: OOM at AllocateBuffer
    # without, 208 ms/step with, r4). Real p256 training needs --remat too.
    "b1_p256": (1, 230, 200, 256, True, "lean"),
    "b16_p128_remat": (16, 100, 80, 128, True, "lean"),
}
EXTRA_SHAPES = {  # The remat flag feeds
    # analytic_train_flops and must match the graph actually built: the
    # tiled extras use the dilated decoder with remat (make_extra), while
    # the DeepLab model's own decoder config (ModelConfig().deeplab) does
    # not remat — its analytic numbers are indicative-only regardless
    # (guard_mfu off, analytic_note set).
    #
    # b1_p384_tiled (mode 'full': forward AND train) is in the DEFAULT
    # list as of r5 — the tiled train-step graphs compile cleanly since
    # the decoder's pad-value rewrite shrank them (r4's remote-compile
    # HTTP 500 no longer reproduces; measured p384 train 397 ms/step,
    # p512 803 ms/step). The fwd-only variant stays for manual runs.
    "b1_p384_tiled_fwd": (1, 370, 350, 384, True, "fwd"),
    "b1_p384_tiled": (1, 370, 350, 384, True, "full"),
    "b1_p512_tiled": (1, 500, 470, 512, True, "full"),
    "b1_p128_deeplab": (1, 100, 80, 128, False, "full"),
}


def _setup():
    import dataclasses

    import jax

    from deepinteract_tpu.models.model import DeepInteract, ModelConfig

    dev = jax.devices()[0]
    global PEAK_FLOPS
    PEAK_FLOPS = resolve_peak_flops(dev.device_kind)
    _log(f"backend={dev.platform} device={dev.device_kind} "
         f"peak_flops={PEAK_FLOPS:.3e}")

    # DI_BENCH_DTYPE=bfloat16 measures the END-TO-END bf16 policy
    # (models/policy.py: GT encoder + attention + decoder; params/norm
    # stats/logits stay f32). DI_BENCH_STEM selects the interaction stem
    # (default: the factorized production default — models/stem.py).
    bench_dtype = os.environ.get("DI_BENCH_DTYPE", "float32")
    if bench_dtype not in ("float32", "bfloat16"):
        raise SystemExit(
            f"DI_BENCH_DTYPE must be 'float32' or 'bfloat16', got {bench_dtype!r}"
        )
    bench_stem = os.environ.get("DI_BENCH_STEM", "factorized")
    if bench_stem not in ("factorized", "materialized"):
        raise SystemExit(
            f"DI_BENCH_STEM must be 'factorized' or 'materialized', "
            f"got {bench_stem!r}")

    def make_model(remat=False, attention_impl="auto", dtype=None,
                   stem=None):
        base = ModelConfig()
        return DeepInteract(dataclasses.replace(
            base,
            gnn=dataclasses.replace(base.gnn, attention_impl=attention_impl),
            decoder=dataclasses.replace(base.decoder, remat=remat),
            compute_dtype=dtype or bench_dtype,
            interaction_stem=stem or bench_stem,
        ))

    def make_extra(**overrides):
        base = ModelConfig(
            gnn=dataclasses.replace(
                ModelConfig().gnn,
                node_count_limit=overrides.pop("node_count_limit", 2304)),
            decoder=dataclasses.replace(
                ModelConfig().decoder,
                # Long-context tiles need remat like p256: the tile-scan
                # backward's residuals (decoder activations x tile count)
                # exceed HBM without it, and the un-remat graph crashes
                # the remote compile helper outright.
                remat=overrides.pop("remat", True)),
            compute_dtype=bench_dtype,
            interaction_stem=bench_stem,
        )
        return DeepInteract(dataclasses.replace(base, **overrides))

    return {
        "dev": dev,
        "bench_dtype": bench_dtype,
        "bench_stem": bench_stem,
        "make_model": make_model,
        "make_extra": make_extra,
        "scan_k": int(os.environ.get("DI_BENCH_SCAN", "8")),
    }


def _section_names(platform: str) -> list:
    """Default section order, most-important first (VERDICT r4 item 1):
    the headline bucket (which folds in the Pallas-vs-jnp A/B on TPU),
    then the large-batch config that crosses the throughput north star,
    then the reference-regime p256, the long-context tiled forward (the
    one real-TPU >256 data point, prioritized over eval), then eval and
    the b16 scaling point. The wall-budget tracker in
    ``_run_sections_isolated`` skips (with explicit entries) whatever
    does not fit. The ab_p128/ab_p256 standalone sections are manual-only
    (DI_BENCH_SECTION=ab_p256): the default A/B rides inside b1_p128."""
    if os.environ.get("DI_BENCH_FAST"):
        return ["b1_p128"]
    # b16_p128_remat is NOT in the default list: the measured scaling is
    # NEGATIVE (620 ms/step scanned = 25.8 c/s vs b8's 33.6, tools/
    # scan_ab.py r5 — the chip saturates at b8), so the budget it would
    # consume is better spent on eval_path. Run it manually via
    # DI_BENCH_SECTION=b16_p128_remat. Likewise b8_p128_remat (f32):
    # superseded as the throughput flagship by b8_p128_bf16 (52 vs 33
    # c/s), its budget instead buys the full b1_p384_tiled TRAIN section
    # — the r4 'tiled train crashes the remote compile helper' limitation
    # fell to the r5 decoder rewrite (measured: p384 train compiles 95 s,
    # runs 397 ms/step; p512 803 ms/step), so the >256-residue tier's
    # training now lands in the driver artifact, not only its forward.
    names = ["b1_p128", "stem_ab", "precision_ab", "b8_p128_bf16",
             "b1_p256", "b1_p384_tiled", "eval_path", "screening",
             "assembly", "saturation", "mesh_serving", "rollover",
             "elasticity", "recovery", "attribution", "input_pipeline"]
    if os.environ.get("DI_TUNING_STORE"):
        # Tuned-vs-default A/B row (right after the headline bucket so a
        # budget-truncated run still lands it): only when an operator
        # points DI_TUNING_STORE at a persisted store — there is nothing
        # to A/B against otherwise.
        names.insert(1, "tuned_ab")
    if os.environ.get("DI_BENCH_EXTRA"):
        names += [n for n in EXTRA_SHAPES if n not in names]
    return names


def _run_bucket_section(label: str, ctx, detail) -> None:
    import jax

    from deepinteract_tpu.training.optim import OptimConfig
    from deepinteract_tpu.training.steps import create_train_state

    if label in BUCKET_SHAPES:
        spec = BUCKET_SHAPES[label]
        bs, n1, n2, pad, remat, mode = spec[:6]
        bucket_dtype = spec[6] if len(spec) > 6 else None
        bench_model = ctx["make_model"](remat=remat, dtype=bucket_dtype)
        extra = False
    else:
        bs, n1, n2, pad, remat, mode = EXTRA_SHAPES[label]
        extra = True
        if label == "b1_p128_deeplab":
            bench_model = ctx["make_extra"](interact_module_type="deeplab")
        elif label.startswith("b1_p384_tiled"):
            bench_model = ctx["make_extra"](tile_pair_map=True, tile_size=128,
                                            node_count_limit=4096)
        else:  # b1_p512_tiled — 2x the reference's 256-residue cap
            bench_model = ctx["make_extra"](tile_pair_map=True,
                                            node_count_limit=4096)

    entry = None
    for attempt in range(2):
        try:
            batch = _make_batch(bs, n1, n2, pad)
            state = create_train_state(
                bench_model, jax.tree_util.tree_map(lambda x: x[:1], batch),
                optim_cfg=OptimConfig(steps_per_epoch=100, num_epochs=50),
            )
            entry = bench_bucket(bench_model, state, batch, label, detail,
                                 remat, ctx["scan_k"], guard_mfu=not extra,
                                 mode=mode)
            break
        except Exception as exc:
            if attempt == 1 or not _is_transient(exc):
                raise
            _log(f"{label}: transient failure, retrying bucket: "
                 f"{str(exc).splitlines()[0][:200]}")
    if extra and entry is not None:
        # analytic_forward_flops models the dilated stack; for these
        # alternative architectures it is indicative only.
        detail["buckets"][label]["analytic_note"] = (
            "analytic FLOPs assume the dilated decoder")
    if label == "b1_p128" and ctx["dev"].platform == "tpu" and entry:
        _run_inline_ab(entry, state, batch, ctx, detail)


def _child_time_left() -> float:
    """Seconds until the parent's section timeout kills this child (set
    via DI_BENCH_CHILD_DEADLINE); inf when running standalone."""
    deadline = os.environ.get("DI_BENCH_CHILD_DEADLINE")
    return float(deadline) - time.time() if deadline else float("inf")


def _run_inline_ab(bucket_entry, state, batch, ctx, detail) -> None:
    """Pallas-vs-jnp A/B folded into the headline section (VERDICT r4
    item 1): the bucket's own 'auto' measurements ARE the Pallas side
    (auto = Pallas wherever supported — see GTConfig.attention_impl), so
    only the jnp-forced forward + train steps compile here. The bucket's
    train state is reused via ``state.replace(apply_fn=...)`` — the
    forced model shares its exact param tree, and a fresh
    ``create_train_state`` would pay another init compile through the
    tunnel. Halves skip with a recorded reason when the parent's section
    deadline is too close (the r5 rehearsal lost the A/B to the section
    timeout).

    Gen-2 additions (ISSUE-10): the jnp side also measures the SCANNED
    train step — single-dispatch numbers carry ±10-20% tunnel spread
    (BASELINE.md) and cannot decide routing, so ``pallas_speedup_
    train_scan`` is the decision-grade ratio — and, when DI_ATTENTION_AB
    points at an evidence file, the measured speedups are recorded there
    so ``attention_impl='auto'`` demonstrably falls back to jnp on
    buckets where the kernel loses (ops/pallas_attention.py:
    resolve_attention_impl)."""
    import jax

    from deepinteract_tpu.training.steps import (
        multi_train_step,
        stack_microbatches,
        train_step,
    )

    ab = {"note": ("pallas-side numbers reused from the b1_p128 bucket "
                   "(auto = pallas); jnp side forced. train_scan is the "
                   "decision-grade ratio (scanned dispatch)"),
          "pallas": {"forward_ms": bucket_entry.get("forward_ms"),
                     "train_ms": bucket_entry.get("train_ms"),
                     "train_scan_ms_per_step":
                         bucket_entry.get("train_scan_ms_per_step")}}
    try:
        m_jnp = ctx["make_model"](attention_impl="jnp")
        if _child_time_left() < 120:
            ab["jnp"] = {"skipped": "section deadline too close"}
        else:
            fwd = jax.jit(
                lambda params, bstats, b: m_jnp.apply(
                    {"params": params, "batch_stats": bstats},
                    b.graph1, b.graph2, train=False,
                )
            )
            _, ft, _ = _time_compiled(
                fwd, (state.params, state.batch_stats, batch))
            ab["jnp"] = {"forward_ms": ft["median"] * 1e3}
        detail["attention_ab_b1_p128"] = ab
        _dump_partial(detail)

        if _child_time_left() < 180:
            ab["jnp"].setdefault("skipped", "section deadline too close")
        else:
            s_jnp = state.replace(apply_fn=m_jnp.apply)
            tstep = jax.jit(lambda s, b: train_step(s, b))
            _, tt, _ = _time_compiled(tstep, (s_jnp, batch))
            ab["jnp"]["train_ms"] = tt["median"] * 1e3
            _dump_partial(detail)
            # jnp scanned train (decision-grade half, ISSUE-10): same
            # protocol as the bucket's own scan measurement.
            scan_k = ctx["scan_k"]
            if (_child_time_left() >= 180
                    and bucket_entry.get("train_scan_ms_per_step")):
                stacked = stack_microbatches([batch] * scan_k)
                mstep = jax.jit(lambda s, bst: multi_train_step(s, bst))
                _, mt, _ = _time_compiled(
                    mstep, (s_jnp, stacked),
                    iters=max(ITERS // 4, 3), reps=min(REPS, 3))
                ab["jnp"]["train_scan_ms_per_step"] = (
                    mt["median"] * 1e3 / scan_k)
        if ab["jnp"].get("forward_ms") and ab["pallas"].get("forward_ms"):
            ab["pallas_speedup_forward"] = (
                ab["jnp"]["forward_ms"] / ab["pallas"]["forward_ms"])
        if ab["jnp"].get("train_ms") and ab["pallas"].get("train_ms"):
            ab["pallas_speedup_train"] = (
                ab["jnp"]["train_ms"] / ab["pallas"]["train_ms"])
        if (ab["jnp"].get("train_scan_ms_per_step")
                and ab["pallas"].get("train_scan_ms_per_step")):
            ab["pallas_speedup_train_scan"] = (
                ab["jnp"]["train_scan_ms_per_step"]
                / ab["pallas"]["train_scan_ms_per_step"])
        _record_attention_evidence(ab, 1, 128, ctx["bench_dtype"])
    except Exception as exc:
        ab["error"] = str(exc).splitlines()[0][:300] if str(exc) else repr(exc)
    detail["attention_ab_b1_p128"] = ab
    _log(json.dumps({"attention_ab_b1_p128": ab}))
    _dump_partial(detail)


def _record_attention_evidence(ab, batch, pad, dtype) -> None:
    """Persist measured Pallas-vs-jnp speedups into the DI_ATTENTION_AB
    evidence file (when set) so auto routing can demote the kernel on
    buckets where it measurably lost — the autotune guard that makes the
    BENCH_r05 0.97x forward default unshippable (ISSUE-10)."""
    from deepinteract_tpu.ops.pallas_attention import (
        attention_ab_path,
        record_attention_ab,
    )

    path = attention_ab_path()
    if not path:
        return
    speedups = {k: ab[k] for k in ("pallas_speedup_forward",
                                   "pallas_speedup_train",
                                   "pallas_speedup_train_scan") if k in ab}
    if not speedups:
        return
    record_attention_ab(
        path, batch, pad, dtype,
        forward_speedup=speedups.get("pallas_speedup_forward"),
        train_speedup=speedups.get("pallas_speedup_train"),
        train_scan_speedup=speedups.get("pallas_speedup_train_scan"))
    ab["evidence_recorded"] = path


def _run_ab_section(pad: int, ctx, detail) -> None:
    """Pallas-vs-jnp A/B at one bucket: forced impls so 'auto' heuristics
    cannot hide a regression; measured on forward + train step."""
    import jax

    from deepinteract_tpu.models.model import ModelConfig
    from deepinteract_tpu.ops.pallas_attention import supports_config
    from deepinteract_tpu.training.optim import OptimConfig
    from deepinteract_tpu.training.steps import create_train_state, train_step

    n1, n2 = {128: (100, 80), 256: (230, 200)}[pad]
    key = f"attention_ab_b1_p{pad}"
    ab = {}
    # The measured models come from ctx["make_model"], which builds the
    # flagship ModelConfig — thread ITS hidden/num_heads into the guard
    # instead of relying on supports() defaults (ISSUE-2 satellite: the
    # head-dim floor must evaluate the measured configuration).
    gnn_cfg = ModelConfig().gnn
    for impl in ("jnp", "pallas"):
        if impl == "pallas" and not supports_config(gnn_cfg, pad):
            ab["pallas"] = {"skipped": f"kernel does not support pad {pad}"}
            continue
        # p256 train needs decoder remat (same HBM constraint as the
        # b1_p256 bucket; without it the step OOMs).
        m = ctx["make_model"](remat=(pad >= 256), attention_impl=impl)
        batch = _make_batch(1, n1, n2, pad)
        state = create_train_state(
            m, batch,
            optim_cfg=OptimConfig(steps_per_epoch=100, num_epochs=50),
        )
        fwd = jax.jit(
            lambda params, bstats, b, _m=m: _m.apply(
                {"params": params, "batch_stats": bstats},
                b.graph1, b.graph2, train=False,
            )
        )
        _, ft, _ = _time_compiled(fwd, (state.params, state.batch_stats, batch))
        tstep = jax.jit(lambda s, b: train_step(s, b))
        _, tt, _ = _time_compiled(tstep, (state, batch))
        ab[impl] = {"forward_ms": ft["median"] * 1e3,
                    "train_ms": tt["median"] * 1e3}
    if "forward_ms" in ab.get("pallas", {}):
        ab["pallas_speedup_forward"] = (
            ab["jnp"]["forward_ms"] / ab["pallas"]["forward_ms"])
        ab["pallas_speedup_train"] = (
            ab["jnp"]["train_ms"] / ab["pallas"]["train_ms"])
    detail[key] = ab
    _log(json.dumps({key: ab}))


def _run_eval_section(ctx, detail) -> None:
    """Eval-path throughput: per-complex dispatch vs batched + scanned eval
    (VERDICT r2 item 6). DIPS-Plus validation is 3,548 complexes/epoch, so
    this ratio is val-epoch wall time."""
    import jax

    from deepinteract_tpu.training.optim import OptimConfig
    from deepinteract_tpu.training.steps import (
        create_train_state,
        eval_step,
        multi_eval_step,
        stack_microbatches,
    )

    model = ctx["make_model"]()
    state = create_train_state(
        model, _make_batch(1, 100, 80, 128),
        optim_cfg=OptimConfig(steps_per_epoch=100, num_epochs=50),
    )
    b1 = _make_batch(1, 100, 80, 128)
    es = jax.jit(lambda s, b: eval_step(s, b))
    _, et1, _ = _time_compiled(es, (state, b1))
    detail["eval_path_b128"] = {
        "eval_b1_ms": et1["median"] * 1e3,
        "eval_b1_complexes_per_sec": 1.0 / et1["median"],
    }
    _dump_partial(detail)
    b8 = _make_batch(8, 100, 80, 128)
    stacked = stack_microbatches([b8] * 8)
    mes = jax.jit(lambda s, bs: multi_eval_step(s, bs))
    _, et64, _ = _time_compiled(mes, (state, stacked),
                                iters=max(ITERS // 4, 3), reps=min(REPS, 3))
    ev = {
        "eval_b1_ms": et1["median"] * 1e3,
        "eval_b1_complexes_per_sec": 1.0 / et1["median"],
        "eval_b8_scan8_ms_per_complex": et64["median"] * 1e3 / 64,
        "eval_b8_scan8_complexes_per_sec": 64.0 / et64["median"],
        "speedup": (64.0 / et64["median"]) / (1.0 / et1["median"]),
    }
    detail["eval_path_b128"] = ev
    _log(json.dumps({"eval_path_b128": ev}))


def _run_tuned_ab_section(ctx, detail) -> None:
    """Tuned-vs-default A/B at the bucket named by DI_TUNED_AB_BUCKET
    (default: the headline b1 p128): both sides run the scanned train
    step through the same differenced protocol — the default side is the
    hardcoded config every entry point ships with (tuning/space.py
    ``default_trial``), the tuned side is whatever the store
    (DI_TUNING_STORE) resolved for this device/model/bucket. The row is
    the evidence line for "did tuning actually buy anything here"."""
    import jax

    from deepinteract_tpu.models.model import DeepInteract, ModelConfig
    from deepinteract_tpu.training.optim import OptimConfig
    from deepinteract_tpu.training.steps import (
        create_train_state,
        multi_train_step,
        stack_microbatches,
    )
    from deepinteract_tpu.tuning import consume
    from deepinteract_tpu.tuning.space import (
        apply_to_model_config,
        apply_to_optim_config,
        default_trial,
    )
    from deepinteract_tpu.tuning.store import TuningStore

    store_path = os.environ["DI_TUNING_STORE"]
    bs, pad = (int(v) for v in
               os.environ.get("DI_TUNED_AB_BUCKET", "1x128").split("x"))
    n1, n2 = {128: (100, 80), 256: (230, 200)}.get(pad, (pad - 28, pad - 48))
    base_cfg = ModelConfig()
    row = {"store": store_path, "bucket": f"b{bs}_p{pad}"}
    detail["tuned_ab"] = row
    store = TuningStore.load(store_path)
    adopted = consume.lookup(store, base_cfg, bs, pad)
    if adopted is None:
        row["skipped"] = (f"no tuning-store entry for b{bs}_p{pad} on this "
                          "device/model")
        return
    row["config"] = adopted.config.to_dict()
    row["source"] = adopted.source
    for side in ("default", "tuned"):
        trial = default_trial() if side == "default" else adopted.config
        scan_k = (trial.scan_k
                  if side == "tuned" and adopted.scan_k_applies
                  else ctx["scan_k"])
        model = DeepInteract(apply_to_model_config(base_cfg, trial))
        batch = _make_batch(bs, n1, n2, pad)
        state = create_train_state(
            model, jax.tree_util.tree_map(lambda x: x[:1], batch),
            # The tuned side runs the microbatch (grad-accum) setting it
            # was measured with; the default side the hardcoded default.
            optim_cfg=apply_to_optim_config(
                OptimConfig(steps_per_epoch=100, num_epochs=50), trial),
        )
        stacked = stack_microbatches([batch] * scan_k)
        mstep = jax.jit(lambda s, bst: multi_train_step(s, bst))
        mc, mt, _ = _time_compiled(mstep, (state, stacked),
                                   iters=max(ITERS // 4, 3),
                                   reps=min(REPS, 3))
        row[side] = {
            "scan_k": scan_k,
            "train_scan_ms_per_step": mt["median"] * 1e3 / scan_k,
            "train_scan_complexes_per_sec": bs * scan_k / mt["median"],
            "compile_s": mc,
        }
        _dump_partial(detail)
    row["tuned_speedup"] = (row["default"]["train_scan_ms_per_step"]
                            / row["tuned"]["train_scan_ms_per_step"])
    _log(json.dumps({"tuned_ab": row}))
    _dump_partial(detail)


def _run_stem_ab_section(ctx, detail) -> None:
    """Factorized-vs-materialized interaction stem A/B at the headline
    bucket: scanned train + forward through the shared differenced
    protocol, same param values on both sides (one init, shared via
    ``state.replace(apply_fn=...)`` — the two stems share one param
    tree by construction, models/stem.py). Memory deltas come from
    each side's compiled forward ``memory_analysis()``."""
    import jax

    from deepinteract_tpu.training.optim import OptimConfig
    from deepinteract_tpu.training.steps import (
        create_train_state,
        multi_train_step,
        stack_microbatches,
    )

    scan_k = ctx["scan_k"]
    row = {"bucket": "b1_p128", "compute_dtype": ctx["bench_dtype"]}
    detail["stem_ab"] = row
    batch = _make_batch(1, 100, 80, 128)
    base_model = ctx["make_model"](stem="factorized")
    state = create_train_state(
        base_model, batch,
        optim_cfg=OptimConfig(steps_per_epoch=100, num_epochs=50),
    )
    for side in ("factorized", "materialized"):
        model = ctx["make_model"](stem=side)
        fwd = jax.jit(
            lambda params, bstats, b, _m=model: _m.apply(
                {"params": params, "batch_stats": bstats},
                b.graph1, b.graph2, train=False,
            )
        )
        _, ft, _ = _time_compiled(fwd, (state.params, state.batch_stats, batch))
        entry = {"forward_ms": ft["median"] * 1e3}
        if ft.get("memory"):
            entry["forward_peak_temp_bytes"] = ft["memory"][
                "temp_size_in_bytes"]
        s_side = state.replace(apply_fn=model.apply)
        stacked = stack_microbatches([batch] * scan_k)
        mstep = jax.jit(lambda st, bst: multi_train_step(st, bst))
        _, mt, _ = _time_compiled(mstep, (s_side, stacked),
                                  iters=max(ITERS // 4, 3),
                                  reps=min(REPS, 3))
        entry["train_scan_ms_per_step"] = mt["median"] * 1e3 / scan_k
        row[side] = entry
        _dump_partial(detail)
    f, m = row["factorized"], row["materialized"]
    row["factorized_speedup_forward"] = m["forward_ms"] / f["forward_ms"]
    row["factorized_speedup_train"] = (
        m["train_scan_ms_per_step"] / f["train_scan_ms_per_step"])
    if "forward_peak_temp_bytes" in f and "forward_peak_temp_bytes" in m:
        row["factorized_temp_bytes_ratio"] = (
            f["forward_peak_temp_bytes"] / max(m["forward_peak_temp_bytes"], 1))
    _log(json.dumps({"stem_ab": row}))
    _dump_partial(detail)


def _run_precision_ab_section(ctx, detail) -> None:
    """End-to-end f32-vs-bf16 dtype policy A/B at the b8 flagship
    (scanned train, remat — the throughput regime where bandwidth
    matters): both sides share param values (params are float32 under
    either policy, models/policy.py), so this isolates the compute-dtype
    effect."""
    import jax

    from deepinteract_tpu.training.optim import OptimConfig
    from deepinteract_tpu.training.steps import (
        create_train_state,
        multi_train_step,
        stack_microbatches,
    )

    scan_k = ctx["scan_k"]
    row = {"bucket": "b8_p128_remat", "stem": ctx["bench_stem"]}
    detail["precision_ab"] = row
    batch = _make_batch(8, 100, 80, 128)
    base_model = ctx["make_model"](remat=True, dtype="float32")
    state = create_train_state(
        base_model, jax.tree_util.tree_map(lambda x: x[:1], batch),
        optim_cfg=OptimConfig(steps_per_epoch=100, num_epochs=50),
    )
    stacked = stack_microbatches([batch] * scan_k)
    for dtype in ("float32", "bfloat16"):
        model = ctx["make_model"](remat=True, dtype=dtype)
        s_side = state.replace(apply_fn=model.apply)
        mstep = jax.jit(lambda st, bst: multi_train_step(st, bst))
        mc, mt, _ = _time_compiled(mstep, (s_side, stacked),
                                   iters=max(ITERS // 4, 3),
                                   reps=min(REPS, 3))
        entry = {
            "train_scan_ms_per_step": mt["median"] * 1e3 / scan_k,
            "train_scan_complexes_per_sec": 8 * scan_k / mt["median"],
            "compile_s": mc,
        }
        if mt.get("memory"):
            entry["train_peak_temp_bytes"] = mt["memory"][
                "temp_size_in_bytes"]
        row[dtype] = entry
        _dump_partial(detail)
    row["bf16_speedup_train"] = (
        row["float32"]["train_scan_ms_per_step"]
        / row["bfloat16"]["train_scan_ms_per_step"])
    _log(json.dumps({"precision_ab": row}))
    _dump_partial(detail)


def _run_screening_section(ctx, detail) -> None:
    """Bulk-screening throughput: split-phase all-vs-all scoring (N
    encoder passes + N^2 micro-batched decodes over the embedding cache,
    deepinteract_tpu/screening) vs the NAIVE loop — one monolithic
    ``engine.predict`` per pair, which re-encodes every chain O(N) times.

    Protocol: a full warm-up screen first compiles every split-phase
    executable (throwaway embedding cache), mirroring the warm-up predict
    on the naive side, so both figures are device execution, not compile
    luck. Every decode already fetches its probabilities to host
    (np.asarray — tuning/timing.py's materialization guarantee), so plain
    wall timing over the batch of work is sound. The naive side times a
    SAMPLE of pairs (its per-pair cost is flat by construction: same
    bucket, same executable) to keep the section inside its budget."""
    import time as _time

    from deepinteract_tpu.screening import (
        ChainLibrary,
        EmbeddingCache,
        ScreenConfig,
        ScreenRunner,
        enumerate_pairs,
    )
    from deepinteract_tpu.serving import EngineConfig, InferenceEngine

    n_chains = int(os.environ.get("DI_BENCH_SCREEN_CHAINS", "12"))
    naive_sample = int(os.environ.get("DI_BENCH_SCREEN_NAIVE_SAMPLE", "12"))
    library = ChainLibrary.synthetic(n_chains, 40, 60, seed=7)
    pairs = enumerate_pairs(library)
    engine = InferenceEngine(
        ctx["make_model"]().cfg,
        cfg=EngineConfig(max_batch=8, result_cache_size=0))
    entry = {"chains": len(library), "pairs": len(pairs),
             "interaction_stem": engine.model.cfg.interaction_stem,
             "compute_dtype": ctx["bench_dtype"]}
    detail["screening"] = entry
    try:
        runner = ScreenRunner(engine, cache=EmbeddingCache(),
                              cfg=ScreenConfig(top_k=10, decode_batch=8,
                                               encode_batch=8))
        # Warm-up screen: pays every encode/decode compile.
        runner.screen(library, pairs)
        entry["compile_inventory"] = dict(engine.stats()["compiled_buckets"])
        _dump_partial(detail)

        # Measured screen, cold embedding cache (the steady-state screen
        # cost: every chain encoded once, every pair decoded once).
        runner_cold = ScreenRunner(engine, cache=EmbeddingCache(),
                                   cfg=runner.cfg)
        t0 = _time.perf_counter()
        cold = runner_cold.screen(library, pairs)
        cold_s = _time.perf_counter() - t0
        entry["screen_pairs_per_sec"] = round(cold.pairs_scored / cold_s, 3)
        entry["screen_elapsed_s"] = round(cold_s, 3)
        entry["encode_reuse_ratio"] = round(cold.encode_reuse_ratio, 2)
        entry["encode_seconds"] = round(cold.encode_seconds, 3)
        entry["decode_seconds"] = round(cold.decode_seconds, 3)
        entry["decode_batches"] = cold.decode_batches
        _dump_partial(detail)

        # Re-screen with the warm cache: zero encoder passes — what a
        # library-resident serving process pays per new query set.
        t0 = _time.perf_counter()
        warm = runner_cold.screen(library, pairs)
        warm_s = _time.perf_counter() - t0
        entry["rescreen_pairs_per_sec"] = round(
            warm.pairs_scored / warm_s, 3)
        entry["emb_cache_hit_rate"] = round(
            warm.emb_cache.get("hit_rate", 0.0), 3)
        entry["rescreen_encodes"] = warm.encodes_executed
        _dump_partial(detail)

        # Naive loop: one monolithic predict per pair. The monolithic
        # executable is separate from the split-phase ones, so warm it
        # explicitly, then time a flat per-pair sample.
        def raw_pair(c1, c2):
            return {"graph1": library[c1].raw, "graph2": library[c2].raw,
                    "examples": np.zeros((0, 3), np.int32)}

        engine.predict(raw_pair(*pairs[0]))  # compile + warm
        sample = pairs[:naive_sample]
        t0 = _time.perf_counter()
        for c1, c2 in sample:
            engine.predict(raw_pair(c1, c2))
        naive_s = _time.perf_counter() - t0
        entry["naive_sample_pairs"] = len(sample)
        entry["naive_pairs_per_sec"] = round(len(sample) / naive_s, 3)
        entry["speedup_vs_naive"] = round(
            entry["screen_pairs_per_sec"] / entry["naive_pairs_per_sec"], 2)
        entry["note"] = (
            "naive = sequential monolithic predict per pair (re-encodes "
            "every chain O(N) times); screen = split-phase encode-once + "
            "micro-batched decode. Timed wall-clock with host-fetched "
            "results; compiles excluded from both sides")
        _dump_partial(detail)

        # Indexed funnel (ISSUE-17): amortize the library encodes into a
        # persistent partitioned index ONCE, then serve ranked-partner
        # queries through the pooled-embedding pre-filter — each query
        # decodes only its top-M survivors instead of the full library
        # row. indexed_pairs_per_sec counts candidate pairs RETIRED per
        # second of query wall (pre-filter reject OR survivor decode) —
        # the figure that scales with library size and is comparable to
        # screen_pairs_per_sec above; query_p50_ms is the end-to-end
        # ranked-partner latency an indexed /screen caller sees. Compile
        # cost excluded by one warm query, same discipline as the rest
        # of this section.
        import shutil
        import tempfile

        from deepinteract_tpu.index import (
            ChainIndex,
            IndexedQueryRunner,
            QueryConfig,
            build_index,
        )

        # Defaults sized for the CPU rehearsal inside this section's
        # ~420s wall estimate: flagship-model decode costs ~1.8s/pair
        # on CPU, so top_m=8 keeps a query to one decode batch. TPU
        # rounds raise these via env (top_m 32+, more queries, 100k
        # chains is the stated target) — gated keys are re-blessed there.
        idx_chains = int(os.environ.get("DI_BENCH_INDEX_CHAINS", "1000"))
        idx_top_m = int(os.environ.get("DI_BENCH_INDEX_TOP_M", "8"))
        idx_queries = int(os.environ.get("DI_BENCH_INDEX_QUERIES", "3"))
        if _child_time_left() < 150:
            # Too close to the section deadline to build + query: a
            # half-measured subsection killed mid-decode would lose the
            # gated keys ("parsed": null class) — skip loudly instead.
            entry["indexed"] = {"skipped": "insufficient section budget "
                                           "left for the indexed funnel"}
            _log(json.dumps({"screening": entry}))
            _dump_partial(detail)
            return
        idx_library = ChainLibrary.synthetic(idx_chains, 40, 60, seed=11)
        idx_dir = tempfile.mkdtemp(prefix="di_bench_index_")
        indexed = {"chains": idx_chains, "top_m": idx_top_m}
        entry["indexed"] = indexed
        try:
            t0 = _time.perf_counter()
            build = build_index(engine, idx_library, idx_dir,
                                partition_size=64, encode_batch=8,
                                cache=EmbeddingCache())
            indexed["build_s"] = round(_time.perf_counter() - t0, 3)
            indexed["partitions"] = build.partitions_total
            index = ChainIndex.open(idx_dir)
            qrunner = IndexedQueryRunner(
                engine, index,
                cfg=QueryConfig(top_m=idx_top_m, top_k=5, decode_batch=8))
            ids = idx_library.ids()
            qids = [ids[(i * len(ids)) // idx_queries]
                    for i in range(idx_queries)]
            qrunner.query_from_index(qids[0])  # warm decode executables
            lat, candidates, decoded, frac = [], 0, 0, 0.0
            for qid in qids:
                t0 = _time.perf_counter()
                res = qrunner.query_from_index(qid)
                lat.append(_time.perf_counter() - t0)
                candidates += res.candidates
                decoded += res.pairs_decoded
                frac = res.prefilter_survivor_frac
            lat.sort()
            indexed["queries"] = len(qids)
            indexed["indexed_pairs_per_sec"] = round(
                candidates / sum(lat), 3)
            indexed["query_p50_ms"] = round(
                _nearest_rank(lat, 0.50) * 1e3, 3)
            indexed["query_p90_ms"] = round(
                _nearest_rank(lat, 0.90) * 1e3, 3)
            indexed["prefilter_survivor_frac"] = round(frac, 4)
            indexed["pairs_decoded"] = decoded
        finally:
            shutil.rmtree(idx_dir, ignore_errors=True)
    finally:
        engine.close()
    _log(json.dumps({"screening": entry}))
    _dump_partial(detail)


def _run_assembly_section(ctx, detail) -> None:
    """k-chain assembly throughput (ISSUE-19): one complex of
    ``DI_BENCH_ASM_CHAINS`` chains through the real AssemblyRunner —
    C(k,2) canonical-oriented pairs, each unique chain encoded EXACTLY
    once, decodes micro-batched through the engine's AOT inventory, and
    the interface graph assembled at the end.

    Protocol mirrors the screening section: a full warm-up assemble
    first pays every encode/decode compile (throwaway embedding cache),
    then the measured assemble runs with a FRESH cache so the
    steady-state figure includes its k cold encodes — and so
    ``unique_encodes`` lands at exactly k, the encode-once invariant
    tools/check_perf_regression.py gates as an absolute ceiling
    (``assembly.chains`` is the contract-carried bar: any growth means
    a pair re-encoded a chain and O(k) silently became O(k^2)). The
    control pass is off here — it doubles the decode bill and its
    scientific value (input-independence) is asserted end-to-end by the
    CLI/serving tests, not a throughput row."""
    import time as _time

    from deepinteract_tpu.assembly import AssemblyConfig, AssemblyRunner
    from deepinteract_tpu.screening import ChainLibrary, EmbeddingCache
    from deepinteract_tpu.serving import EngineConfig, InferenceEngine

    k = int(os.environ.get("DI_BENCH_ASM_CHAINS", "6"))
    library = ChainLibrary.synthetic(k, 40, 60, seed=17)
    engine = InferenceEngine(
        ctx["make_model"]().cfg,
        cfg=EngineConfig(max_batch=8, result_cache_size=0))
    entry = {"chains": k,
             "interaction_stem": engine.model.cfg.interaction_stem,
             "compute_dtype": ctx["bench_dtype"]}
    detail["assembly"] = entry
    try:
        cfg = AssemblyConfig(top_k=10, decode_batch=8, encode_batch=8,
                             control=False, keep_maps=False)
        # Warm-up assemble: pays every encode/decode compile.
        AssemblyRunner(engine, cache=EmbeddingCache(), cfg=cfg).assemble(
            library)
        entry["compile_inventory"] = dict(
            engine.stats()["compiled_buckets"])
        _dump_partial(detail)

        # Measured assemble, fresh cache: k cold encodes + C(k,2)
        # decodes — the steady-state cost of scoring one new complex.
        runner = AssemblyRunner(engine, cache=EmbeddingCache(), cfg=cfg)
        t0 = _time.perf_counter()
        result = runner.assemble(library)
        elapsed = _time.perf_counter() - t0
        entry["pairs"] = result.pairs_total
        entry["pairs_per_sec"] = round(result.pairs_scored / elapsed, 3)
        entry["unique_encodes"] = result.unique_encodes
        entry["encode_cache_hits"] = result.encode_cache_hits
        entry["decode_batches"] = result.decode_batches
        entry["interface_edges"] = len(result.interface["edges"])
        entry["encode_seconds"] = round(result.encode_seconds, 3)
        entry["decode_seconds"] = round(result.decode_seconds, 3)
        entry["elapsed_s"] = round(elapsed, 3)
        if result.unique_encodes > result.chains:
            raise RuntimeError(
                f"encode-once violated: {result.unique_encodes} encodes "
                f"for {result.chains} chains")
    finally:
        engine.close()
    _log(json.dumps({"assembly": entry}))
    _dump_partial(detail)


def _nearest_rank(sorted_samples, q):
    """Nearest-rank percentile over PRE-SORTED samples — the one
    definition behind the gated saturation/rollover p99 keys (two
    sections drifting on quantile convention would make their gated
    ratios incomparable)."""
    return sorted_samples[min(len(sorted_samples) - 1,
                              int(q * len(sorted_samples)))]


def _run_saturation_section(ctx, detail) -> None:
    """Overload behavior under deliberate oversubscription (ISSUE-11):
    bounded admission queues + request deadlines + 429/Retry-After
    rejection, measured end to end through the engine's batched path.

    Protocol (all CPU-runnable; absolute figures are device-dependent,
    the RATIO is the contract):

    1. warm every batch-slot executable the run can hit, then measure the
       UNSATURATED baseline with a closed loop of ``max_batch`` workers —
       the same coalescing regime the saturated phase runs in, so the
       p99 comparison isolates queueing, not batching;
    2. drive an OPEN loop at ``DI_BENCH_SAT_OVERSUB`` (default 4x) times
       the measured unsaturated throughput for ``DI_BENCH_SAT_SECONDS``
       against bounded queues (``max_queue_depth == max_batch``: at most
       one full extra batch of queueing, which is what keeps served p99
       inside ~2x the unsaturated p99 while ALL excess load is rejected
       at submit time with a computed retry_after_s);
    3. record served-vs-rejected counts, served p50/p99, the p99 ratio,
       and deadline accounting (every request carries a deadline; zero
       should expire when rejection keeps the queue bounded)."""
    import threading as _threading

    from deepinteract_tpu.screening import ChainLibrary
    from deepinteract_tpu.serving import EngineConfig, InferenceEngine
    from deepinteract_tpu.serving.admission import (
        Deadline,
        DeadlineExceeded,
        Overloaded,
    )

    oversub = float(os.environ.get("DI_BENCH_SAT_OVERSUB", "4"))
    duration_s = float(os.environ.get("DI_BENCH_SAT_SECONDS", "8"))
    unsat_requests = int(os.environ.get("DI_BENCH_SAT_UNSAT", "24"))
    max_batch = 4
    library = ChainLibrary.synthetic(2, 40, 60, seed=13)
    ids = list(library.ids())
    raw = {"graph1": library[ids[0]].raw, "graph2": library[ids[1]].raw,
           "examples": np.zeros((0, 3), np.int32)}
    engine = InferenceEngine(
        ctx["make_model"]().cfg,
        cfg=EngineConfig(max_batch=max_batch, max_delay_ms=2.0,
                         result_cache_size=0,
                         max_queue_depth=max_batch, max_inflight=64))
    entry = {"oversubscription": oversub, "duration_s": duration_s,
             "max_batch": max_batch,
             "max_queue_depth": engine.cfg.max_queue_depth,
             "interaction_stem": engine.model.cfg.interaction_stem,
             "compute_dtype": ctx["bench_dtype"]}
    detail["saturation"] = entry
    try:
        # Warm every coalesced-batch slot size (1, 2, 4) the phases can
        # hit, so neither measurement pays compile luck.
        engine.warmup([(64, 64, s) for s in (1, 2, 4)])
        _dump_partial(detail)

        # Unsaturated baseline: closed loop, max_batch concurrent
        # clients (no queue growth by construction).
        lat_lock = _threading.Lock()
        unsat_lat = []

        def closed_worker(n):
            for _ in range(n):
                t0 = time.perf_counter()
                engine.predict(raw)
                with lat_lock:
                    unsat_lat.append(time.perf_counter() - t0)

        per_worker = max(1, unsat_requests // max_batch)
        threads = [_threading.Thread(target=closed_worker,
                                     args=(per_worker,))
                   for _ in range(max_batch)]
        t0 = time.perf_counter()
        for t in threads:
            t.start()
        for t in threads:
            t.join()
        unsat_s = time.perf_counter() - t0
        unsat_lat.sort()
        unsat_p50 = unsat_lat[len(unsat_lat) // 2]
        unsat_p99 = _nearest_rank(unsat_lat, 0.99)
        unsat_rps = len(unsat_lat) / unsat_s
        entry["unsat_p50_ms"] = round(unsat_p50 * 1e3, 2)
        entry["unsat_p99_ms"] = round(unsat_p99 * 1e3, 2)
        entry["unsat_served_per_sec"] = round(unsat_rps, 3)
        _dump_partial(detail)

        # Saturated phase: open loop at oversub x the measured rate,
        # every request carrying a deadline comfortably above the
        # BOUNDED queue's worst case (the point is that rejection — not
        # deadline expiry — absorbs the excess).
        offered_rps = oversub * unsat_rps
        interval = 1.0 / offered_rps
        deadline_budget = max(2.0, 20.0 * unsat_p99)
        served_lat = []
        failed = {"deadline": 0, "other": 0}
        rejected = []
        futs = []

        def on_done(fut, t_sub):
            exc = fut.exception()
            with lat_lock:
                if exc is None:
                    served_lat.append(time.perf_counter() - t_sub)
                elif isinstance(exc, DeadlineExceeded):
                    failed["deadline"] += 1
                else:
                    failed["other"] += 1

        t_start = time.monotonic()
        next_t = t_start
        while time.monotonic() - t_start < duration_s:
            now = time.monotonic()
            if now < next_t:
                time.sleep(min(next_t - now, 0.002))
                continue
            next_t += interval
            t_sub = time.perf_counter()
            try:
                fut = engine.submit(raw,
                                    deadline=Deadline.after(deadline_budget))
            except Overloaded as exc:
                rejected.append(exc.retry_after_s)
                continue
            except DeadlineExceeded:
                # Under lat_lock: done-callbacks on the flush worker
                # update the same dict concurrently.
                with lat_lock:
                    failed["deadline"] += 1
                continue
            fut.add_done_callback(lambda f, t=t_sub: on_done(f, t))
            futs.append(fut)
        for fut in futs:
            try:
                fut.result(timeout=deadline_budget + 10.0)
            except Exception:
                pass  # already tallied by the callback

        served_lat.sort()
        served = len(served_lat)
        offered = served + len(rejected) + failed["deadline"] + failed["other"]
        entry["offered_per_sec"] = round(offered_rps, 3)
        entry["offered"] = offered
        entry["served"] = served
        entry["rejected"] = len(rejected)
        entry["deadline_expired"] = failed["deadline"]
        entry["failed_other"] = failed["other"]
        entry["reject_rate"] = round(len(rejected) / max(1, offered), 3)
        if rejected:
            entry["retry_after_s_median"] = round(
                sorted(rejected)[len(rejected) // 2], 3)
        if served:
            p50 = served_lat[served // 2]
            p99 = _nearest_rank(served_lat, 0.99)
            entry["served_p50_ms"] = round(p50 * 1e3, 2)
            entry["served_p99_ms"] = round(p99 * 1e3, 2)
            entry["served_per_sec"] = round(served / duration_s, 3)
            entry["p99_ratio"] = round(p99 / max(unsat_p99, 1e-9), 2)
        entry["admission"] = engine.admission.stats()
        entry["note"] = (
            "open-loop oversubscription vs a closed-loop unsaturated "
            "baseline in the same coalescing regime; p99_ratio is the "
            "bounded-queue contract (excess load rejected 429-style at "
            "admission, never queued unboundedly)")
    finally:
        engine.close()
    _log(json.dumps({"saturation": {
        k: entry.get(k) for k in (
            "served", "rejected", "deadline_expired", "served_p99_ms",
            "unsat_p99_ms", "p99_ratio", "served_per_sec", "reject_rate")}}))
    _dump_partial(detail)


def _run_mesh_serving_section(ctx, detail) -> None:
    """Mesh-sharded serving (ISSUE-20): the same engine serving (a) mixed
    small-bucket traffic data-parallel over a mesh vs one chip, and (b) a
    single huge p512 complex with its interaction tensor row-sharded over
    the pair axis vs decoded on one chip.

    Protocol (CPU-rehearsable: the parent injects
    ``--xla_force_host_platform_device_count=8``, so the mesh is 8
    virtual CPU devices sharing ONE physical core — the mesh/1-chip
    RATIOS are then rehearsal figures, honest about that in the note; on
    real multi-chip hardware the same section measures the genuine
    speedups):

    1. closed-loop mixed traffic (two chain shapes, one bucket) against a
       single-device engine, then against a data-axis mesh engine —
       ``throughput_ratio`` = mesh served/sec over single served/sec;
    2. one >256-residue complex (512-bucket, tiled decode) predicted on a
       single device, then on a pair-axis mesh engine —
       ``p512_latency_ms`` (pair-sharded) vs ``p512_single_latency_ms``.

    Uses a COMPACT model config (not ctx['make_model']'s flagship): like
    the rollover section's stub fleet, this section pins the serving-mesh
    LAYER — placement routing, sharded AOT entries, halo-exchanged
    decode — not the architecture's absolute speed."""
    import threading as _threading

    import jax

    from deepinteract_tpu.models.decoder import DecoderConfig
    from deepinteract_tpu.models.geometric_transformer import GTConfig
    from deepinteract_tpu.models.model import ModelConfig
    from deepinteract_tpu.screening import ChainLibrary
    from deepinteract_tpu.serving import EngineConfig, InferenceEngine
    from deepinteract_tpu.serving.fleet import mesh_label

    dc = jax.device_count()
    if dc < 2:
        raise RuntimeError(
            f"mesh_serving needs >=2 devices, have {dc}: set XLA_FLAGS="
            "--xla_force_host_platform_device_count=8 for the CPU "
            "rehearsal or run on real multi-chip hardware")
    data_shape = (min(4, dc), 1)
    pair_shape = (1, min(4, dc))
    requests = int(os.environ.get("DI_BENCH_MESH_REQUESTS", "24"))
    repeats = int(os.environ.get("DI_BENCH_MESH_REPEATS", "3"))
    max_batch = 4
    compact = ModelConfig(
        gnn=GTConfig(num_layers=2, hidden=16, num_heads=2, shared_embed=8,
                     dropout_rate=0.0),
        decoder=DecoderConfig(num_chunks=1, num_channels=8,
                              dilation_cycle=(1,)),
    )
    entry = {"devices": dc, "model": "compact",
             "mesh_shape_data": mesh_label(data_shape),
             "mesh_shape_pair": mesh_label(pair_shape),
             "requests": requests, "max_batch": max_batch}
    detail["mesh_serving"] = entry

    # Mixed small-bucket traffic: two shapes from one bucket so the
    # closed loop exercises coalescing, not bucket churn.
    library = ChainLibrary.synthetic(4, 40, 60, seed=13)
    ids = list(library.ids())
    raws = [{"graph1": library[ids[i]].raw,
             "graph2": library[ids[(i + 1) % len(ids)]].raw,
             "examples": np.zeros((0, 3), np.int32)}
            for i in range(len(ids))]

    def _throughput(mesh_shape) -> float:
        engine = InferenceEngine(
            compact,
            cfg=EngineConfig(max_batch=max_batch, max_delay_ms=2.0,
                             result_cache_size=0, mesh_shape=mesh_shape))
        try:
            engine.warmup([(64, 64, s) for s in (1, 2, 4)])
            counter = {"i": 0}
            lock = _threading.Lock()

            def worker(n):
                for _ in range(n):
                    with lock:
                        raw = raws[counter["i"] % len(raws)]
                        counter["i"] += 1
                    engine.predict(raw)

            per_worker = max(1, requests // max_batch)
            threads = [_threading.Thread(target=worker, args=(per_worker,))
                       for _ in range(max_batch)]
            t0 = time.perf_counter()
            for t in threads:
                t.start()
            for t in threads:
                t.join()
            return (per_worker * max_batch) / (time.perf_counter() - t0)
        finally:
            engine.close()

    single_rps = _throughput(None)
    entry["single_served_per_sec"] = round(single_rps, 3)
    _dump_partial(detail)
    mesh_rps = _throughput(data_shape)
    entry["mesh_served_per_sec"] = round(mesh_rps, 3)
    entry["throughput_ratio"] = round(mesh_rps / max(single_rps, 1e-9), 3)
    _dump_partial(detail)

    # One huge complex: both chains past the top bucket, so the decode
    # runs tiled at the 512 bucket — the regime the pair axis exists for.
    big = ChainLibrary.synthetic(2, 300, 340, seed=17)
    bids = list(big.ids())
    big_raw = {"graph1": big[bids[0]].raw, "graph2": big[bids[1]].raw,
               "examples": np.zeros((0, 3), np.int32)}

    def _p512_latency(mesh_shape) -> float:
        engine = InferenceEngine(
            compact,
            cfg=EngineConfig(max_batch=1, mesh_shape=mesh_shape,
                             pair_shard_threshold=512,
                             result_cache_size=0))
        try:
            engine.predict(big_raw)  # compile + warm
            samples = []
            for _ in range(repeats):
                t0 = time.perf_counter()
                engine.predict(big_raw)
                samples.append(time.perf_counter() - t0)
            samples.sort()
            return samples[len(samples) // 2]
        finally:
            engine.close()

    single_lat = _p512_latency(None)
    entry["p512_single_latency_ms"] = round(single_lat * 1e3, 2)
    _dump_partial(detail)
    pair_lat = _p512_latency(pair_shape)
    entry["p512_latency_ms"] = round(pair_lat * 1e3, 2)
    entry["p512_speedup"] = round(single_lat / max(pair_lat, 1e-9), 3)
    entry["note"] = (
        "compact-model serving-mesh rehearsal; on a shared-core virtual "
        "CPU mesh the ratios carry coordination overhead with no extra "
        "FLOPs, so >1.0 throughput_ratio / p512 speedup is only expected "
        "on real multi-chip hardware")
    _log(json.dumps({"mesh_serving": {
        k: entry.get(k) for k in (
            "throughput_ratio", "single_served_per_sec",
            "mesh_served_per_sec", "p512_latency_ms",
            "p512_single_latency_ms", "p512_speedup", "devices")}}))
    _dump_partial(detail)


def _run_rollover_section(ctx, detail) -> None:
    """Latency disruption of a LIVE warm rollover (ISSUE-13): steady
    closed-loop load through the fleet router while ``POST
    /admin/rollover`` replaces every worker, measured end to end.

    The fleet runs ``serving/worker_stub.py`` null-engine workers with a
    fixed simulated device latency, so the measured numbers isolate the
    FLEET LAYER's contribution — routing-table swap, failover retries in
    the drain race window, replacement warm-wait — which is exactly what
    the zero-downtime contract is about (an engine worker's own latency
    is covered by the other sections). The contract keys:
    ``dropped_requests`` (non-200 answers during the rollover window;
    the bar is ZERO) and ``p99_during_rollover_ms`` vs the steady-state
    p99 measured through the SAME router (the bar is <= 2x)."""
    import tempfile
    import threading as _threading

    from deepinteract_tpu.serving.fleet import (
        FleetConfig,
        WorkerSupervisor,
        request_json,
        stub_worker_cmd,
    )
    from deepinteract_tpu.serving.router import FleetRouter, RouterConfig

    workers = int(os.environ.get("DI_BENCH_ROLLOVER_WORKERS", "2"))
    clients = int(os.environ.get("DI_BENCH_ROLLOVER_CLIENTS", "4"))
    steady_s = float(os.environ.get("DI_BENCH_ROLLOVER_STEADY", "3"))
    load_s = float(os.environ.get("DI_BENCH_ROLLOVER_SECONDS", "8"))
    delay_ms = 20.0
    state_dir = tempfile.mkdtemp(prefix="di_bench_fleet_")
    supervisor = WorkerSupervisor(
        stub_worker_cmd,
        FleetConfig(num_workers=workers, probe_interval_s=0.2,
                    heartbeat_max_age_s=5.0, state_dir=state_dir),
        overrides={"weights_signature": "bench-v1",
                   "delay_ms": delay_ms, "warm_buckets": "64x64/b1",
                   "heartbeat_interval_s": 0.2})
    router = FleetRouter(
        supervisor, port=0,
        cfg=RouterConfig(proxy_timeout_s=10.0, warm_timeout_s=60.0,
                         drain_timeout_s=30.0,
                         required_warm_buckets=("64x64/",)))
    entry = {"workers": workers, "clients": clients,
             "stub_delay_ms": delay_ms, "load_s": load_s,
             "protocol": "closed-loop clients through the router over "
                         "stub workers; rollover mid-window"}
    detail["rollover"] = entry
    try:
        router.start()
        host, port = router.address
        warm_deadline = time.monotonic() + 60.0
        while (len(supervisor.routable_workers()) < workers
               and time.monotonic() < warm_deadline):
            supervisor.poll_once()
            time.sleep(0.05)
        if len(supervisor.routable_workers()) < workers:
            raise RuntimeError("fleet never became fully routable")

        lock = _threading.Lock()

        def post_predict():
            return request_json(host, port, "POST", "/predict",
                                body=b"{}", timeout_s=10.0)

        def closed_loop(samples, stop_at):
            while time.monotonic() < stop_at:
                t0 = time.perf_counter()
                try:
                    status, _ = post_predict()
                except Exception:
                    status = -1
                with lock:
                    samples.append((time.perf_counter() - t0, status))

        def run_phase(seconds):
            samples = []
            stop_at = time.monotonic() + seconds
            threads = [_threading.Thread(target=closed_loop,
                                         args=(samples, stop_at))
                       for _ in range(clients)]
            for t in threads:
                t.start()
            return samples, threads

        # Steady phase: the baseline tail through the SAME router.
        samples, threads = run_phase(steady_s)
        for t in threads:
            t.join()
        lat = sorted(s for s, status in samples if status == 200)
        if not lat:
            raise RuntimeError("steady phase served nothing")
        entry["steady_requests"] = len(samples)
        entry["steady_p50_ms"] = round(lat[len(lat) // 2] * 1e3, 2)
        entry["steady_p99_ms"] = round(
            _nearest_rank(lat, 0.99) * 1e3, 2)
        _dump_partial(detail)

        # Rollover phase: same load, with a live weights rollover fired
        # 1s in (replacement spawn + warm-wait + swap + old drain all
        # land inside the window).
        rollover_result = {}

        def trigger():
            time.sleep(1.0)
            try:
                status, record = request_json(
                    host, port, "POST", "/admin/rollover",
                    body=json.dumps(
                        {"weights_signature": "bench-v2"}).encode(),
                    timeout_s=90.0)
                rollover_result["status"] = status
                rollover_result["record"] = record
            except Exception as exc:
                rollover_result["error"] = repr(exc)

        samples, threads = run_phase(load_s)
        trig = _threading.Thread(target=trigger)
        trig.start()
        for t in threads:
            t.join()
        trig.join(timeout=120.0)
        record = rollover_result.get("record", {})
        if not isinstance(record, dict):
            record = {}
        roll_detail = record.get("rollover", {})
        entry["rollover_http_status"] = rollover_result.get("status")
        # Gate on the ROLLOVER's own outcome (HTTP 200 + the rollover
        # record's ok), not the fleet-wide contract ok — that one means
        # "no circuit open" and could fail the section for an unrelated
        # flapping worker while the rollover itself succeeded.
        entry["rollover_ok"] = (rollover_result.get("status") == 200
                                and bool(roll_detail.get("ok")))
        if not entry["rollover_ok"]:
            # A failed/never-fired rollover must NOT emit the gated
            # contract keys: steady load over an undisturbed old fleet
            # would trivially show 0 drops and a clean p99, and the
            # zero-downtime gate would pass while the capability is
            # broken. Missing keys fail check_perf_regression loudly
            # (the plumbing-regression class).
            raise RuntimeError(
                "rollover did not complete: "
                f"status={rollover_result.get('status')} "
                f"error={rollover_result.get('error')}")
        # The rollover must also land INSIDE the measured window (it
        # fires at t=1s): a slow machine where spawn+warm-wait+drain
        # outlives the sampling phase would gate pre-rollover traffic —
        # trivially clean numbers that measured nothing.
        roll_elapsed = roll_detail.get("elapsed_s")
        if (not isinstance(roll_elapsed, (int, float))
                or 1.0 + float(roll_elapsed) > load_s):
            raise RuntimeError(
                f"rollover (elapsed {roll_elapsed}s, fired at t=1s) did "
                f"not complete inside the {load_s}s load window — the "
                "gated keys would measure undisturbed traffic; raise "
                "DI_BENCH_ROLLOVER_SECONDS on this machine")
        lat = sorted(s for s, status in samples if status == 200)
        dropped = sum(1 for _, status in samples if status != 200)
        entry["requests_during_rollover"] = len(samples)
        entry["dropped_requests"] = dropped
        if lat:
            entry["p99_during_rollover_ms"] = round(
                _nearest_rank(lat, 0.99) * 1e3, 2)
            entry["p99_ratio"] = round(
                entry["p99_during_rollover_ms"]
                / max(entry["steady_p99_ms"], 1e-9), 2)
        entry["rollover_elapsed_s"] = roll_detail.get("elapsed_s")
        entry["old_worker_drain_exit_codes"] = roll_detail.get(
            "drain_exit_codes")
        entry["failovers"] = record.get("failovers")
        # Post-rollover proof: traffic is served by the NEW weights.
        status, payload = post_predict()
        if status == 200 and isinstance(payload, dict):
            entry["post_rollover_signature"] = payload.get(
                "weights_signature")
        entry["note"] = (
            "stub-worker fleet isolates the fleet layer's disruption "
            "(routing swap, drain-race failover, warm-wait) from model "
            "latency; dropped_requests counts every non-200 answer "
            "during the rollover window — the zero-downtime bar is 0")
    finally:
        try:
            router.drain()
        except Exception:
            pass
        import shutil

        shutil.rmtree(state_dir, ignore_errors=True)
    _log(json.dumps({"rollover": {
        k: entry.get(k) for k in (
            "steady_p99_ms", "p99_during_rollover_ms", "p99_ratio",
            "dropped_requests", "requests_during_rollover",
            "rollover_elapsed_s", "failovers", "rollover_ok")}}))
    _dump_partial(detail)


def _run_elasticity_section(ctx, detail) -> None:
    """Elastic-fleet disruption budget (ISSUE-16): a LIVE autoscaler over
    stub workers rides a diurnal-shaped trace — steady trickle, a burst
    that must scale the fleet UP (with a mid-burst preemption injected as
    the expected spot-loss event), then a drop that must scale it back
    DOWN — while closed-loop clients measure the tail end to end.

    Like the rollover section, stub workers with a fixed simulated device
    latency isolate the FLEET LAYER's contribution: warm-before-adopt
    scale-up, release-then-drain scale-down, preemption replacement. The
    gated keys: ``dropped_requests`` (non-200 answers across ALL phases;
    the bar is ZERO — elasticity must never shed correct traffic) and
    ``p99_ratio`` (burst-phase p99 over the steady baseline through the
    SAME router). The section raises — emitting NO gated keys — unless
    the autoscaler actually scaled up, scaled down, AND absorbed the
    preemption: steady numbers over a static fleet would trivially pass."""
    import tempfile
    import threading as _threading

    from deepinteract_tpu.serving.autoscaler import (
        Autoscaler,
        AutoscalerConfig,
    )
    from deepinteract_tpu.serving.fleet import (
        FleetConfig,
        WorkerSupervisor,
        request_json,
        stub_worker_cmd,
    )
    from deepinteract_tpu.serving.router import FleetRouter, RouterConfig

    steady_clients = int(os.environ.get("DI_BENCH_ELASTIC_STEADY_CLIENTS",
                                        "2"))
    burst_clients = int(os.environ.get("DI_BENCH_ELASTIC_BURST_CLIENTS",
                                       "8"))
    steady_s = float(os.environ.get("DI_BENCH_ELASTIC_STEADY", "3"))
    burst_s = float(os.environ.get("DI_BENCH_ELASTIC_BURST", "10"))
    drop_s = float(os.environ.get("DI_BENCH_ELASTIC_DROP", "8"))
    delay_ms = 20.0
    state_dir = tempfile.mkdtemp(prefix="di_bench_elastic_")
    supervisor = WorkerSupervisor(
        stub_worker_cmd,
        FleetConfig(num_workers=1, probe_interval_s=0.15,
                    heartbeat_max_age_s=5.0, state_dir=state_dir),
        overrides={"weights_signature": "bench-v1",
                   "delay_ms": delay_ms,
                   "heartbeat_interval_s": 0.2})
    router = FleetRouter(
        supervisor, port=0,
        cfg=RouterConfig(proxy_timeout_s=10.0, warm_timeout_s=60.0,
                         drain_timeout_s=30.0))
    scaler = Autoscaler(
        supervisor, router,
        cfg=AutoscalerConfig(min_workers=1, max_workers=3,
                             interval_s=0.3, queue_high=1.5,
                             queue_low=0.2, breach_polls=2,
                             cooldown_s=1.5, warm_timeout_s=60.0,
                             drain_timeout_s=30.0),
        overrides={"weights_signature": "bench-v1",
                   "delay_ms": delay_ms,
                   "heartbeat_interval_s": 0.2})
    entry = {"stub_delay_ms": delay_ms,
             "steady_clients": steady_clients,
             "burst_clients": burst_clients,
             "steady_s": steady_s, "burst_s": burst_s, "drop_s": drop_s,
             "protocol": "closed-loop diurnal trace (steady/burst/drop) "
                         "through the router under a live autoscaler; "
                         "one preemption injected mid-burst"}
    detail["elasticity"] = entry
    peak = {"workers": 0}
    try:
        router.start()
        host, port = router.address
        warm_deadline = time.monotonic() + 60.0
        while (not supervisor.routable_workers()
               and time.monotonic() < warm_deadline):
            supervisor.poll_once()
            time.sleep(0.05)
        if not supervisor.routable_workers():
            raise RuntimeError("seed worker never became routable")
        scaler.start()

        lock = _threading.Lock()

        def closed_loop(samples, stop_at):
            while time.monotonic() < stop_at:
                t0 = time.perf_counter()
                try:
                    status, _ = request_json(host, port, "POST",
                                             "/predict", body=b"{}",
                                             timeout_s=10.0)
                except Exception:
                    status = -1
                with lock:
                    samples.append((time.perf_counter() - t0, status))

        def run_phase(clients, seconds):
            samples = []
            stop_at = time.monotonic() + seconds
            threads = [_threading.Thread(target=closed_loop,
                                         args=(samples, stop_at))
                       for _ in range(clients)]
            for t in threads:
                t.start()
            while time.monotonic() < stop_at:
                peak["workers"] = max(
                    peak["workers"],
                    len(supervisor.routable_workers()))
                time.sleep(0.1)
            for t in threads:
                t.join()
            return samples

        # Phase 1 — steady trickle: the baseline tail, fleet at 1.
        samples = run_phase(steady_clients, steady_s)
        lat = sorted(s for s, status in samples if status == 200)
        if not lat:
            raise RuntimeError("steady phase served nothing")
        dropped = sum(1 for _, status in samples if status != 200)
        entry["steady_requests"] = len(samples)
        entry["steady_p99_ms"] = round(_nearest_rank(lat, 0.99) * 1e3, 2)
        _dump_partial(detail)

        # Phase 2 — burst: the autoscaler must grow the fleet; one
        # preemption lands mid-burst as the expected spot-loss event.
        def preempt_mid_burst():
            time.sleep(burst_s / 2.0)
            victims = supervisor.routable_workers()
            if victims:
                supervisor.preempt_worker(victims[-1]["worker_id"])

        trig = _threading.Thread(target=preempt_mid_burst)
        trig.start()
        samples = run_phase(burst_clients, burst_s)
        trig.join(timeout=30.0)
        lat = sorted(s for s, status in samples if status == 200)
        if not lat:
            raise RuntimeError("burst phase served nothing")
        dropped += sum(1 for _, status in samples if status != 200)
        entry["burst_requests"] = len(samples)
        entry["p99_during_scale_ms"] = round(
            _nearest_rank(lat, 0.99) * 1e3, 2)
        entry["p99_ratio"] = round(
            entry["p99_during_scale_ms"]
            / max(entry["steady_p99_ms"], 1e-9), 2)
        _dump_partial(detail)

        # Phase 3 — drop: back to the trickle; the autoscaler must
        # release-and-drain the surplus without dropping the remainder.
        samples = run_phase(steady_clients, drop_s)
        dropped += sum(1 for _, status in samples if status != 200)
        entry["drop_requests"] = len(samples)

        stats = scaler.stats()
        sup_stats = supervisor.stats()
        entry["scale_ups"] = stats["scale_ups"]
        entry["scale_downs"] = stats["scale_downs"]
        entry["autoscale_errors"] = stats["errors"]
        entry["preemptions"] = sup_stats["preemptions"]
        entry["peak_workers"] = peak["workers"]
        entry["final_workers"] = len(supervisor.routable_workers())
        entry["dropped_requests"] = dropped
        # Honest completion: the gated keys mean nothing unless the
        # trace actually exercised every capacity event. A static fleet
        # shows 0 drops and a flat p99 while the capability is broken.
        problems = []
        if entry["scale_ups"] < 1:
            problems.append("never scaled up under the burst")
        if entry["scale_downs"] < 1:
            problems.append("never scaled down after the drop")
        if entry["preemptions"] < 1:
            problems.append("the injected preemption never landed")
        if problems:
            entry.pop("p99_ratio", None)
            entry.pop("dropped_requests", None)
            raise RuntimeError(
                "elasticity trace incomplete — gated keys withheld: "
                + "; ".join(problems)
                + " (raise DI_BENCH_ELASTIC_BURST / _DROP on this "
                  "machine)")
        entry["note"] = (
            "stub-worker fleet isolates the fleet layer's elasticity "
            "cost (warm-before-adopt scale-up, release-then-drain "
            "scale-down, preemption replacement); dropped_requests "
            "counts every non-200 answer across all three phases — "
            "the bar is 0")
    finally:
        try:
            scaler.stop()
        except Exception:
            pass
        try:
            router.drain()
        except Exception:
            pass
        import shutil

        shutil.rmtree(state_dir, ignore_errors=True)
    _log(json.dumps({"elasticity": {
        k: entry.get(k) for k in (
            "steady_p99_ms", "p99_during_scale_ms", "p99_ratio",
            "dropped_requests", "scale_ups", "scale_downs",
            "preemptions", "peak_workers", "final_workers")}}))
    _dump_partial(detail)


def _run_recovery_section(ctx, detail) -> None:
    """Self-healing training MTTR (ISSUE-14): a REAL supervised
    ``cli.train --supervise`` run over a tiny synthetic dataset, its
    child killed -9 mid-epoch one save cadence past the newest
    ``mid/`` checkpoint, then measured end to end: how long from the
    kill to the first resumed training progress (``mttr_s`` — watchdog
    poll + backoff + child respawn + compile-cache-warm restore), and
    how many already-paid optimizer steps the resume re-executed
    (``steps_reexecuted`` — bounded by ``--save_every_steps`` when the
    cursor machinery works; gated as a ceiling even at baseline 0).

    Children run on CPU (JAX_PLATFORMS forced) so a TPU bench round
    cannot deadlock the chip the parent holds — like the rollover
    section, the number isolates the SUPERVISION layer's contribution,
    which is the same on any backend."""
    import signal as _signal
    import subprocess
    import tempfile

    from deepinteract_tpu.data.synthetic import write_tiny_npz_dataset

    save_every = int(os.environ.get("DI_BENCH_RECOVERY_CADENCE", "2"))
    workdir = tempfile.mkdtemp(prefix="di_bench_recovery_")
    data_root = os.path.join(workdir, "data")
    ckpt_dir = os.path.join(workdir, "ckpt")
    n_complexes = 4  # batch 1 -> 4 steps/epoch
    write_tiny_npz_dataset(data_root, n_complexes=n_complexes, seed=0)
    entry = {"save_every_steps": save_every,
             "steps_per_epoch": n_complexes,
             "protocol": "supervised cli.train child killed -9 mid-epoch; "
                         "MTTR = kill to first resumed heartbeat "
                         "progress (CPU rehearsal)"}
    detail["recovery"] = entry
    cmd = [sys.executable, "-m", "deepinteract_tpu.cli.train",
           "--supervise", "--dips_root", data_root, "--ckpt_dir", ckpt_dir,
           "--save_every_steps", str(save_every),
           "--heartbeat_seconds", "0.2", "--watch_interval_s", "0.1",
           "--hang_timeout_s", "120", "--start_grace_s", "300",
           "--train_restart_backoff_s", "0.2",
           "--compile_cache_dir", os.path.join(workdir, "cc"),
           "--num_gnn_layers", "1", "--num_gnn_hidden_channels", "8",
           "--num_gnn_attention_heads", "2", "--num_interact_layers", "1",
           "--num_interact_hidden_channels", "8",
           "--steps_per_dispatch", "1", "--log_every", "1",
           "--seed", "7", "--num_epochs", "3"]
    env = dict(os.environ, JAX_PLATFORMS="cpu")
    hb_path = os.path.join(ckpt_dir, "obs", "heartbeat_p0.json")
    state_path = os.path.join(ckpt_dir, "train_supervisor_state.json")
    sidecar_path = os.path.join(ckpt_dir, "trainer_state.json")
    proc = subprocess.Popen(cmd, env=env, cwd=workdir,
                            stdout=subprocess.PIPE,
                            stderr=subprocess.STDOUT, text=True)

    def read_json(path):
        try:
            with open(path, encoding="utf-8") as fh:
                return json.load(fh)
        except (OSError, ValueError):
            return None

    def global_step(payload) -> int:
        if not isinstance(payload, dict):
            return -1
        epoch, step = payload.get("epoch"), payload.get("step")
        if not isinstance(epoch, int) or not isinstance(step, int):
            return -1
        return epoch * n_complexes + step

    try:
        # Wait for a mid-epoch-1 cursor save, then kill one cadence in:
        # the re-executed work is then genuinely > 0 and <= cadence.
        kill_pid = None
        saved_global = None
        deadline = time.monotonic() + 420.0
        while time.monotonic() < deadline and kill_pid is None:
            time.sleep(0.05)
            side = read_json(sidecar_path) or {}
            cur = side.get("cursor") or {}
            hb = read_json(hb_path)
            if (cur.get("epoch") == 1 and cur.get("batch_index", 0) >= 1
                    and global_step(hb) > cur["epoch"] * n_complexes
                    + cur["batch_index"]):
                state = read_json(state_path) or {}
                kill_pid = state.get("child_pid")
                saved_global = (cur["epoch"] * n_complexes
                                + cur["batch_index"])
        if kill_pid is None:
            raise RuntimeError("never observed a mid-epoch cursor save "
                               "+ post-save progress inside the window")
        killed_global = global_step(read_json(hb_path))
        t_kill = time.monotonic()
        os.kill(int(kill_pid), _signal.SIGKILL)
        # The cursor may have advanced between the poll and the kill;
        # re-read it now the child is dead (the file is quiescent until
        # the restarted child overwrites it after the backoff) so
        # steps_reexecuted is computed against the TRUE resume position.
        side = read_json(sidecar_path) or {}
        cur = side.get("cursor") or {}
        if isinstance(cur.get("epoch"), int) \
                and isinstance(cur.get("batch_index"), int):
            saved_global = max(saved_global, cur["epoch"] * n_complexes
                               + cur["batch_index"])
        entry["kill_step_global"] = killed_global
        entry["saved_step_global"] = saved_global
        _dump_partial(detail)

        # MTTR: first heartbeat written by a DIFFERENT pid showing step
        # progress — the resumed child actually training again.
        old_tag = f":{kill_pid}"
        mttr = None
        deadline = time.monotonic() + 420.0
        while time.monotonic() < deadline and mttr is None:
            time.sleep(0.02)
            hb = read_json(hb_path)
            if (isinstance(hb, dict)
                    and not str(hb.get("host", "")).endswith(old_tag)
                    and global_step(hb) >= saved_global):
                mttr = time.monotonic() - t_kill
        if mttr is None:
            raise RuntimeError("resumed child never showed progress")
        out, _ = proc.communicate(timeout=420.0)
        record = json.loads(
            [ln for ln in out.splitlines() if ln.strip()][-1])
        if proc.returncode != 0 or not record.get("ok"):
            raise RuntimeError(
                f"supervised run ended dishonestly: rc={proc.returncode} "
                f"contract={record}")
        entry["mttr_s"] = round(mttr, 2)
        entry["steps_reexecuted"] = max(0, killed_global - saved_global)
        entry["restarts"] = record.get("restarts")
        entry["supervisor_ok"] = bool(record.get("ok"))
        entry["note"] = (
            "CPU rehearsal: mttr is watchdog+respawn+restore latency "
            "through a real kill -9; steps_reexecuted must stay <= "
            "save_every_steps (the cursor bound) — parity itself is "
            "pinned by the tier-1 chaos tests")
    finally:
        if proc.poll() is None:
            proc.kill()
        import shutil

        shutil.rmtree(workdir, ignore_errors=True)
    _log(json.dumps({"recovery": {
        k: entry.get(k) for k in (
            "mttr_s", "steps_reexecuted", "save_every_steps",
            "kill_step_global", "saved_step_global", "restarts",
            "supervisor_ok")}}))
    _dump_partial(detail)


def _run_input_pipeline_section(ctx, detail) -> None:
    """Stepped-loader throughput across the loader→step boundary
    (ISSUE-15): the REAL BucketedLoader feeding the REAL Trainer epoch
    loop, measured with batch placement inline vs double-buffered on the
    input pipeline's placement thread (--device_prefetch), under both
    per-step and scanned dispatch. ``prefetch_overlap_ratio`` (scanned
    prefetch-on rate / scanned inline rate) is the contract-line figure
    gated in tools/check_perf_regression.py — unlike the bucket sections
    (device-resident arguments, zero input pipeline), these rates pay
    batch assembly + stacking + h2d, so the ratio isolates exactly what
    moving placement off the dispatch critical path buys."""
    import jax

    from deepinteract_tpu.data.loader import BucketedLoader, InMemoryDataset
    from deepinteract_tpu.data.synthetic import random_raw_complex
    from deepinteract_tpu.training.loop import LoopConfig, Trainer
    from deepinteract_tpu.training.optim import OptimConfig

    n_complexes = int(os.environ.get("DI_BENCH_IP_COMPLEXES", "16"))
    batch = int(os.environ.get("DI_BENCH_IP_BATCH", "2"))
    scan_k = int(os.environ.get("DI_BENCH_IP_SCAN", "4"))
    epochs = 2  # epoch 1 pays the compiles; epoch 2 is the steady rate
    rng = np.random.default_rng(5)
    raws = [random_raw_complex(int(rng.integers(90, 126)),
                               int(rng.integers(90, 126)), rng)
            for _ in range(n_complexes)]
    model = ctx["make_model"]()
    entry = {"n_complexes": n_complexes, "batch": batch, "scan_k": scan_k}
    detail["input_pipeline"] = entry

    def stepped_rate(k: int, prefetch: bool) -> float:
        loader = BucketedLoader(InMemoryDataset(list(raws)),
                                batch_size=batch, drop_remainder=True)
        trainer = Trainer(
            model,
            LoopConfig(num_epochs=epochs, steps_per_dispatch=k,
                       log_every=0, device_prefetch=prefetch,
                       preemption_guard=False, span_log=False),
            OptimConfig(lr=1e-4,
                        steps_per_epoch=max(loader.num_batches(), 1),
                        num_epochs=epochs),
            log_fn=lambda _m: None,
        )
        t0 = time.perf_counter()
        state = trainer.init_state(next(iter(loader)))
        _, history = trainer.fit(state, loader)
        steady_s = history[-1]["epoch_seconds"]  # epoch 1 paid compiles
        complexes = loader.num_batches() * batch
        _log(f"input_pipeline: k={k} prefetch={prefetch} "
             f"steady_epoch={steady_s:.2f}s "
             f"({complexes / steady_s:.2f} c/s; total "
             f"{time.perf_counter() - t0:.0f}s incl. compiles)")
        return complexes / steady_s

    # Scanned dispatch first (the gated ratio), then per-step; inline
    # before prefetch within each so a deadline kill loses the ratio,
    # never ships it half-measured.
    entry["scan_inline_cps"] = stepped_rate(scan_k, False)
    _dump_partial(detail)
    entry["scan_prefetch_cps"] = stepped_rate(scan_k, True)
    entry["prefetch_overlap_ratio"] = (
        entry["scan_prefetch_cps"] / entry["scan_inline_cps"])
    _dump_partial(detail)
    if _child_time_left() > 240:
        entry["per_step_inline_cps"] = stepped_rate(1, False)
        entry["per_step_prefetch_cps"] = stepped_rate(1, True)
        entry["per_step_overlap_ratio"] = (
            entry["per_step_prefetch_cps"] / entry["per_step_inline_cps"])
    else:
        entry["per_step_skipped"] = "section deadline too close"
    _log(json.dumps({"input_pipeline": entry}))
    _dump_partial(detail)


def _run_attribution_section(ctx, detail) -> None:
    """Device-time attribution of the serving forward (ISSUE-8): capture
    a jax.profiler trace around a few warm predicts, parse it to per-op
    device time (deepinteract_tpu/obs/device.py + attribution.py), and
    reconcile against the compiled forward's HLO launch census — so the
    bench artifact carries WHERE the milliseconds go, not just how many
    there are. The top-3 ops and their shares land in the contract line.

    DI_BENCH_PROFILE_DIR keeps the raw capture for
    ``cli/attribute.py``/TensorBoard; default is a temp dir."""
    import tempfile

    import jax  # noqa: F401  (profiler backend must be live)

    from deepinteract_tpu.obs import attribution as obs_attr
    from deepinteract_tpu.obs import device as obs_device
    from deepinteract_tpu.obs import hloquery
    from deepinteract_tpu.obs import spans as obs_spans
    from deepinteract_tpu.screening import ChainLibrary
    from deepinteract_tpu.serving import EngineConfig, InferenceEngine

    iters = int(os.environ.get("DI_BENCH_ATTR_ITERS", "3"))
    library = ChainLibrary.synthetic(2, 100, 110, seed=11)
    ids = list(library.ids())
    raw = {"graph1": library[ids[0]].raw, "graph2": library[ids[1]].raw,
           "examples": np.zeros((0, 3), np.int32)}
    engine = InferenceEngine(
        ctx["make_model"]().cfg,
        cfg=EngineConfig(max_batch=1, max_delay_ms=0.0,
                         result_cache_size=0))
    entry = {"iters": iters,
             "interaction_stem": engine.model.cfg.interaction_stem,
             "compute_dtype": ctx["bench_dtype"]}
    detail["attribution"] = entry
    try:
        engine.predict(raw)  # compile + warm outside the capture
        profile_dir = (os.environ.get("DI_BENCH_PROFILE_DIR")
                       or tempfile.mkdtemp(prefix="di_bench_prof_"))
        with obs_device.capture(profile_dir):
            for _ in range(iters):
                with obs_spans.span("predict"):
                    engine.predict(raw)
        census = None
        executables = list(engine._executables.values())
        if executables:
            census = dict(hloquery.census_compiled(executables[0]))
        trace = obs_device.load_profile(profile_dir,
                                        phase_names=("predict",))
        fwd_flops = analytic_forward_flops(1, 128)["forward_flops"]
        report = obs_attr.build_report(
            trace, top_n=10,
            analytic_flops={"predict": float(fwd_flops)},
            peak_flops=PEAK_FLOPS,
            census=census, census_instances=iters,
            census_meta={"source": "serving_forward_entry"})
        entry["profile_dir"] = profile_dir
        entry["total_device_ms"] = report["total_device_ms"]
        entry["op_launches"] = report["op_launches"]
        entry["top_ops"] = [
            {"name": o["name"], "total_ms": o["total_ms"],
             "share": o["share"], "op_class": o["op_class"],
             "bound_guess": o["bound_guess"]}
            for o in report["top_ops"][:5]]
        entry["phases"] = report["phases"]
        if "remask" in report:
            entry["remask"] = report["remask"]
    finally:
        engine.close()
    _log(json.dumps({"attribution": {
        k: entry.get(k) for k in ("total_device_ms", "op_launches",
                                  "top_ops", "remask")}}))
    _dump_partial(detail)


def _section_result_key(name: str):
    """Where a section's result (or error) lives in the detail dict:
    (container, key). Buckets nest under 'buckets'; the A/B and eval
    sections use the same top-level keys their successes always used."""
    if name == "eval_path":
        return None, "eval_path_b128"
    if name in ("tuned_ab", "stem_ab", "precision_ab", "screening",
                "assembly", "saturation", "rollover", "elasticity",
                "recovery", "attribution", "input_pipeline"):
        return None, name
    if name.startswith("ab_p"):
        return None, f"attention_ab_b1_p{name[4:]}"
    return "buckets", name


def _record_section_error(detail, name: str, msg: str, kind="error") -> None:
    container, key = _section_result_key(name)
    target = detail[container] if container else detail
    entry = target.get(key)
    if isinstance(entry, dict) and entry:
        # Annotate, never replace: the entry may hold sub-measurements
        # already captured by the partial-dump mechanism.
        entry.setdefault(kind, msg)
    else:
        target[key] = {kind: msg}
    _log(json.dumps({key: {kind: msg}}))


def _run_section(name: str, ctx, detail) -> None:
    if name == "eval_path":
        _run_eval_section(ctx, detail)
    elif name == "tuned_ab":
        _run_tuned_ab_section(ctx, detail)
    elif name == "stem_ab":
        _run_stem_ab_section(ctx, detail)
    elif name == "precision_ab":
        _run_precision_ab_section(ctx, detail)
    elif name == "screening":
        _run_screening_section(ctx, detail)
    elif name == "assembly":
        _run_assembly_section(ctx, detail)
    elif name == "saturation":
        _run_saturation_section(ctx, detail)
    elif name == "mesh_serving":
        _run_mesh_serving_section(ctx, detail)
    elif name == "rollover":
        _run_rollover_section(ctx, detail)
    elif name == "elasticity":
        _run_elasticity_section(ctx, detail)
    elif name == "recovery":
        _run_recovery_section(ctx, detail)
    elif name == "attribution":
        _run_attribution_section(ctx, detail)
    elif name == "input_pipeline":
        _run_input_pipeline_section(ctx, detail)
    elif name.startswith("ab_p"):
        _run_ab_section(int(name[4:]), ctx, detail)
    else:
        _run_bucket_section(name, ctx, detail)


def _build_headline(detail, scan_k) -> dict:
    """The stdout contract record from the b1_p128 result (or a value-0
    record when the headline bucket failed, so the driver records a failed
    measurement instead of an empty file). Headline = scanned train
    throughput (what a real training run sustains); the per-dispatch step
    figure rides along as a compatibility key (ADVICE r2)."""
    entry = detail["buckets"].get("b1_p128", {})
    if "train_scan_complexes_per_sec" in entry:
        # Headline value = MEDIAN differenced scan sample (ISSUE-2
        # satellite, r5 advisor finding): differenced-sample minima are
        # biased OPTIMISTIC — interference inside the t1 run deflates the
        # sample — so the r5 min-headline could overstate throughput by up
        # to its 10% admission band. The min now rides along as a
        # supplementary key (still useful as a loaded-host cross-check:
        # a concurrent CPU hog inflates the median ~8% while the min
        # stays put), admitted under the same clamp/band guards as
        # before, but it no longer sets value/vs_baseline.
        bs = max(1, int(entry.get("batch", 1)))
        value = entry["train_scan_complexes_per_sec"]
        metric = f"train_complexes_per_sec_b1_p128_scan{scan_k}"
        extra = {"headline_protocol": "median of differenced scan samples"}
        min_s = entry.get("train_scan_ms_per_step_min")
        med_s = entry.get("train_scan_ms_per_step")
        proto = entry.get("scan_timing_protocol", {})
        min_ok = (min_s and med_s
                  and proto.get("clamped_samples", 1) == 0
                  and min_s >= 0.9 * med_s)
        if min_ok:
            extra["train_scan_complexes_per_sec_min_sample"] = round(
                bs / (min_s / 1e3), 2)
    elif "train_complexes_per_sec" in entry:
        value = entry["train_complexes_per_sec"]
        metric = "train_step_complexes_per_sec_b1_p128"
        extra = {}
    else:
        return {
            "metric": f"train_complexes_per_sec_b1_p128_scan{scan_k}",
            "value": 0.0, "unit": "complexes/s", "vs_baseline": 0.0,
            "interaction_stem": detail.get("interaction_stem"),
            "compute_dtype": detail.get("compute_dtype"),
        }
    line = {
        "metric": metric,
        "value": round(value, 2),
        "unit": "complexes/s",
        "vs_baseline": round(value / CPU_BASELINE_COMPLEXES_PER_SEC, 2),
        # Measurement provenance: which stem/precision produced the number
        # (ISSUE-5 contract keys).
        "interaction_stem": entry.get("interaction_stem",
                                      detail.get("interaction_stem")),
        "compute_dtype": entry.get("compute_dtype",
                                   detail.get("compute_dtype")),
        **extra,
    }
    if "interaction_bytes" in entry:
        line["interaction_bytes"] = entry["interaction_bytes"]
    if "train_complexes_per_sec" in entry:
        line["train_step_complexes_per_sec_b1_p128"] = round(
            entry["train_complexes_per_sec"], 2)
    if "analytic_train_mfu" in entry:
        line["analytic_train_mfu"] = round(entry["analytic_train_mfu"], 4)
    if entry.get("timing_warnings"):
        # The headline was measured under an unstable differenced
        # protocol — say so in the contract itself so the regression
        # gate (tools/check_perf_regression.py) widens its tolerance
        # instead of trusting a noisy figure at face value.
        line["timing_warning"] = "; ".join(entry["timing_warnings"])
    ab = detail.get("attention_ab_b1_p128", {})
    if isinstance(ab, dict) and any(k.startswith("pallas_speedup")
                                    for k in ab):
        # The Pallas-vs-jnp A/B rides in the contract line (ISSUE-10
        # acceptance): the scanned ratio is the decision-grade one; the
        # evidence_recorded path says auto-routing was fed the result.
        line["attention_ab"] = {
            k: round(ab[k], 4) for k in ("pallas_speedup_forward",
                                         "pallas_speedup_train",
                                         "pallas_speedup_train_scan")
            if isinstance(ab.get(k), (int, float))}
        if "evidence_recorded" in ab:
            line["attention_ab"]["evidence_recorded"] = (
                ab["evidence_recorded"])
    attribution = detail.get("attribution", {})
    if "top_ops" in attribution:
        # Device-time attribution of the serving forward (ISSUE-8): the
        # top-3 ops by measured device time and their shares, so the
        # driver artifact ranks wall-clock sinks without re-parsing the
        # raw trace.
        line["attribution"] = {
            "total_device_ms": attribution.get("total_device_ms"),
            "top_ops": [
                {"name": o["name"], "total_ms": o["total_ms"],
                 "share": o["share"]}
                for o in attribution["top_ops"][:3]],
        }
        if "remask" in attribution:
            line["attribution"]["remask_share"] = (
                attribution["remask"].get("share"))
    saturation = detail.get("saturation", {})
    if "served_p99_ms" in saturation:
        # Overload-safety contract keys (ISSUE-11): bounded-queue p99
        # ratio under oversubscription, served-vs-rejected split, and
        # deadline accounting — the driver artifact shows the server
        # degrades by REJECTING, not by queueing unboundedly.
        line["saturation"] = {
            k: saturation[k]
            for k in ("p99_ratio", "served_p99_ms", "unsat_p99_ms",
                      "served_per_sec", "reject_rate", "served",
                      "rejected", "deadline_expired", "oversubscription")
            if k in saturation}
    rollover = detail.get("rollover", {})
    if "p99_during_rollover_ms" in rollover:
        # Zero-downtime rollover contract keys (ISSUE-13): the tail
        # through a live weights rollover vs the same router's steady
        # state, and the dropped-request count whose bar is zero. Gated
        # in tools/check_perf_regression.py.
        line["rollover"] = {
            k: rollover[k]
            for k in ("p99_during_rollover_ms", "steady_p99_ms",
                      "p99_ratio", "dropped_requests",
                      "requests_during_rollover", "rollover_elapsed_s",
                      "failovers", "workers")
            if k in rollover}
    elasticity = detail.get("elasticity", {})
    if "p99_during_scale_ms" in elasticity:
        # Elastic-fleet contract keys (ISSUE-16): burst-phase tail over
        # the steady baseline while the autoscaler grows/shrinks the
        # fleet and absorbs a preemption, and the dropped-request count
        # whose bar is zero. Gated in tools/check_perf_regression.py;
        # only emitted when the trace actually scaled up, scaled down,
        # and landed the preemption (_run_elasticity_section raises
        # otherwise).
        line["elasticity"] = {
            k: elasticity[k]
            for k in ("p99_during_scale_ms", "steady_p99_ms",
                      "p99_ratio", "dropped_requests", "scale_ups",
                      "scale_downs", "preemptions", "peak_workers",
                      "final_workers")
            if k in elasticity}
    recovery = detail.get("recovery", {})
    if "mttr_s" in recovery:
        # Self-healing training contract keys (ISSUE-14): kill-to-first-
        # resumed-step MTTR under the supervisor, and the re-executed
        # work bound (<= --save_every_steps). Gated in
        # tools/check_perf_regression.py; like the rollover section, the
        # gated keys are only emitted when the supervised run itself
        # completed honestly (_run_recovery_section raises otherwise).
        line["recovery"] = {
            k: recovery[k]
            for k in ("mttr_s", "steps_reexecuted", "save_every_steps",
                      "restarts", "supervisor_ok")
            if k in recovery}
    input_pipeline = detail.get("input_pipeline", {})
    if "prefetch_overlap_ratio" in input_pipeline:
        # Input-pipeline contract keys (ISSUE-15): the stepped-loader
        # rate with placement double-buffered on the prefetch thread vs
        # inline, under scanned (gated) and per-step dispatch. Gated in
        # tools/check_perf_regression.py.
        line["input_pipeline"] = {
            k: round(input_pipeline[k], 4)
            for k in ("prefetch_overlap_ratio", "scan_prefetch_cps",
                      "scan_inline_cps", "per_step_overlap_ratio",
                      "per_step_prefetch_cps", "per_step_inline_cps")
            if isinstance(input_pipeline.get(k), (int, float))}
    screening = detail.get("screening", {})
    if "screen_pairs_per_sec" in screening:
        # The bulk-screening workload's own throughput row (ISSUE-6):
        # pairs/sec, the amortized-encode win over the naive per-pair
        # loop, and the embedding-cache hit rate of a warm re-screen.
        line["screening"] = {
            k: screening[k]
            for k in ("screen_pairs_per_sec", "naive_pairs_per_sec",
                      "speedup_vs_naive", "encode_reuse_ratio",
                      "emb_cache_hit_rate", "pairs", "chains")
            if k in screening}
        if isinstance(screening.get("indexed"), dict):
            # Proteome-index funnel contract keys (ISSUE-17): ranked-
            # partner throughput/latency against a prebuilt partitioned
            # index, and the pre-filter's survivor fraction. The first
            # two are gated in tools/check_perf_regression.py.
            idx = screening["indexed"]
            line["screening"]["indexed"] = {
                k: idx[k]
                for k in ("indexed_pairs_per_sec", "query_p50_ms",
                          "prefilter_survivor_frac", "chains", "top_m")
                if k in idx}
    mesh_serving = detail.get("mesh_serving", {})
    if "throughput_ratio" in mesh_serving:
        # Mesh-sharded serving contract keys (ISSUE-20): data-parallel
        # mixed-traffic throughput vs one chip and the pair-sharded p512
        # single-complex latency vs one chip. throughput_ratio and
        # p512_latency_ms are gated in tools/check_perf_regression.py.
        line["mesh_serving"] = {
            k: mesh_serving[k]
            for k in ("throughput_ratio", "single_served_per_sec",
                      "mesh_served_per_sec", "p512_latency_ms",
                      "p512_single_latency_ms", "p512_speedup",
                      "mesh_shape_data", "mesh_shape_pair", "devices")
            if k in mesh_serving}
    assembly = detail.get("assembly", {})
    if "pairs_per_sec" in assembly:
        # Assembly contract keys (ISSUE-19): k-chain complex scoring
        # throughput and the encode-once invariant (unique_encodes <=
        # chains — the contract carries its own ceiling). Both gated in
        # tools/check_perf_regression.py.
        line["assembly"] = {
            k: assembly[k]
            for k in ("pairs_per_sec", "unique_encodes", "chains",
                      "pairs", "decode_batches", "interface_edges")
            if k in assembly}
    if _is_partial(detail):
        # Sections were skipped/failed under the wall budget: the record
        # says so itself instead of looking complete-but-thin.
        line["partial"] = True
    return line


def _is_partial(detail) -> bool:
    """True when any section of this run was skipped, errored, or timed
    out — consumers of the contract line must know the artifact is not the
    full default section list."""
    if detail.get("section_incidents"):
        return True
    candidates = list(detail.get("buckets", {}).values())
    candidates += [v for k, v in detail.items()
                   if k.startswith(("attention_ab", "eval_path", "tuned_ab",
                                    "stem_ab", "precision_ab", "screening",
                                    "assembly", "saturation", "mesh_serving",
                                    "rollover", "elasticity", "recovery",
                                    "attribution", "input_pipeline"))
                   and isinstance(v, dict)]
    return any(("skipped" in c or "error" in c) for c in candidates
               if isinstance(c, dict))


def _emit_headline(detail, scan_k) -> None:
    print(json.dumps(_build_headline(detail, scan_k)), flush=True)


def _merge_fragment(detail, fragment) -> None:
    for k, v in fragment.items():
        if k == "buckets":
            detail["buckets"].update(v)
        else:
            detail[k] = v


def _run_sections_isolated(names, detail, scan_k) -> None:
    """Run each section in a FRESH subprocess. The axon tunnel's remote
    compile helper degrades within long-lived client processes (observed:
    p256 compiles return HTTP 500 after a few large compiles in the same
    process but succeed from a fresh one), so process isolation is the
    reliable way to get every bucket. Also bounds each section's wall time
    and shields the run from a single section crashing the interpreter."""
    import subprocess
    import tempfile

    for name in names:
        # Wall-budget gate (VERDICT r4 item 1): a section that cannot fit
        # the remaining budget is recorded as an explicit skip — the
        # artifact stays complete-by-construction and the process exits
        # rc=0 before the driver's own kill.
        remaining = BUDGET_S - (time.monotonic() - _T0)
        est = SECTION_EST_S.get(name, 300)
        if remaining < 0.8 * est:
            _record_section_error(
                detail, name,
                f"wall budget: {remaining:.0f}s remaining < ~{est}s "
                f"section estimate", kind="skipped")
            continue
        timeout_s = min(
            float(os.environ.get("DI_BENCH_SECTION_TIMEOUT", "900")),
            max(remaining - 20.0, 60.0))
        frag = None
        with tempfile.NamedTemporaryFile(suffix=".json", delete=False) as fh:
            out_path = fh.name
        env = dict(os.environ,
                   DI_BENCH_SECTION=name, DI_BENCH_OUT=out_path,
                   # Lets the child skip optional sub-measurements (the
                   # inline A/B halves) that cannot finish before the kill.
                   DI_BENCH_CHILD_DEADLINE=str(time.time() + timeout_s))
        if name == "mesh_serving":
            # The mesh section needs devices to shard over; on a CPU-only
            # host give the child 8 virtual devices (the flag is inert on
            # the TPU backend — real chips win).
            xla = env.get("XLA_FLAGS", "")
            if "xla_force_host_platform_device_count" not in xla:
                env["XLA_FLAGS"] = (
                    xla + " --xla_force_host_platform_device_count=8").strip()
        err = None
        try:
            proc = subprocess.run(
                [sys.executable, os.path.abspath(__file__)],
                env=env, timeout=timeout_s,
                stdout=subprocess.DEVNULL, stderr=None,
            )
            if proc.returncode != 0:
                err = f"section exited rc={proc.returncode}"
        except subprocess.TimeoutExpired:
            err = f"section timed out after {timeout_s:.0f}s"
        except Exception as exc:
            err = str(exc).splitlines()[0][:300]
        # The child dumps its fragment incrementally, so even a timeout or
        # crash leaves the sub-measurements that already finished.
        try:
            if os.path.getsize(out_path) > 0:
                with open(out_path) as fh:
                    frag = json.load(fh)
        except Exception:
            pass
        finally:
            try:
                os.unlink(out_path)
            except OSError:
                pass
        if frag:
            _merge_fragment(detail, frag)
            if err:
                detail.setdefault("section_incidents", {})[name] = (
                    f"{err} (partial rows merged)")
                _log(json.dumps({name: {"incident": err}}))
        elif err:
            _record_section_error(detail, name, err)
        else:
            _record_section_error(detail, name, "section produced no output")
        _dump_parent(detail)
        if name == "b1_p128":
            _emit_headline(detail, scan_k)


def main() -> None:
    section = os.environ.get("DI_BENCH_SECTION")
    ctx = _setup()
    detail = {"backend": ctx["dev"].platform,
              "device_kind": ctx["dev"].device_kind,
              "iters": ITERS, "reps": REPS,
              "compute_dtype": ctx["bench_dtype"],
              "interaction_stem": ctx["bench_stem"], "buckets": {}}
    scan_k = ctx["scan_k"]

    if section:
        # Child mode: run ONE section, dump the detail fragment, print
        # nothing on stdout (the parent owns the contract line).
        try:
            _run_section(section, ctx, detail)
        except Exception as exc:
            msg = str(exc).splitlines()[0][:300] if str(exc) else repr(exc)
            _record_section_error(detail, section, msg)
        out = os.environ.get("DI_BENCH_OUT")
        if out:
            with open(out, "w") as fh:
                json.dump(detail, fh)
        return

    names = _section_names(ctx["dev"].platform)
    if os.environ.get("DI_BENCH_INLINE"):
        for name in names:
            try:
                _run_section(name, ctx, detail)
            except Exception as exc:
                msg = str(exc).splitlines()[0][:300] if str(exc) else repr(exc)
                _record_section_error(detail, name, msg)
            if name == "b1_p128":
                _emit_headline(detail, scan_k)
    else:
        _run_sections_isolated(names, detail, scan_k)

    detail["cpu_baseline_complexes_per_sec"] = CPU_BASELINE_COMPLEXES_PER_SEC
    detail["peak_flops_assumed"] = PEAK_FLOPS
    detail["mfu_note"] = (
        "analytic_* figures use hand-derived matmul/conv FLOPs (trustworthy, "
        "<=1); xla_* figures use compiled cost_analysis flops, which "
        "over-count under remat/fusion — cross-check only"
    )
    _log("DETAIL " + json.dumps(detail))
    # Re-print the contract record as the FINAL terminal line (ISSUE-2
    # satellite): the driver parses the last line of its capture, and in
    # r5 that was the multi-hundred-KB "DETAIL ..." stderr dump —
    # BENCH_r05.json landed with "parsed": null and the headline survived
    # only in builder logs. The early print after the b1_p128 section
    # stays as crash insurance; this one is what the capture parses.
    _emit_headline(detail, scan_k)


if __name__ == "__main__":
    main()
