"""Benchmark harness: flagship forward + full train step on the live backend.

Contract (driver): prints exactly ONE JSON line on stdout —
``{"metric": ..., "value": N, "unit": ..., "vs_baseline": N}``.
All detail (per-bucket timings, compile times, FLOPs, MFU estimates) goes to
stderr as a JSON object, so it lands in BENCH_r{N}.json's tail too.

The reference repo publishes no throughput numbers (BASELINE.md: "Throughput
/ latency numbers: none recorded anywhere in repo"), so ``vs_baseline`` is
the ratio against the north-star proxy from BASELINE.json — the same model's
measured single-process CPU throughput (the "CPU/DGL path" stand-in; target
is >=8x). The CPU number is pinned below from a one-time measurement on this
image (see CPU_BASELINE_COMPLEXES_PER_SEC) rather than re-measured each run:
CPU XLA compilation alone costs minutes and the driver runs this file on a
wall-clock budget.

Model: reference-default flagship — 2 Geometric Transformer layers, 128
hidden, 4 heads, kNN=20, 14-chunk dilated SE-ResNet decoder
(project/utils/deepinteract_utils.py:1012-1019).
"""

from __future__ import annotations

import json
import os
import sys
import time

import numpy as np

# One-time measurement of the jitted flagship *train step* on this image's CPU
# backend (batch 1, 128-pad, single process): see BENCH_NOTES in git history.
CPU_BASELINE_COMPLEXES_PER_SEC = float(
    os.environ.get("DI_CPU_BASELINE_CPS", "2.23")
)

# Peak bf16 matmul throughput used for the MFU estimate. The axon tunnel
# exposes a "TPU v5 lite" (v5e): 197 TFLOP/s bf16. Override with
# DI_PEAK_FLOPS if the hardware changes.
PEAK_FLOPS = float(os.environ.get("DI_PEAK_FLOPS", "197e12"))

WARMUP = 2
ITERS = int(os.environ.get("DI_BENCH_ITERS", "20"))

# NOTE: do NOT enable JAX_COMPILATION_CACHE_DIR here — executable
# serialization hangs through the axon PJRT tunnel (observed: forward
# compile 40s without the cache, >9 min stuck with it).


def _log(msg: str) -> None:
    print(msg, file=sys.stderr, flush=True)


def _time_compiled(fn, args, iters=ITERS):
    """(compile_seconds, per_call_seconds, flops_or_None) for a jitted fn."""
    import jax

    t0 = time.perf_counter()
    compiled = fn.lower(*args).compile()
    compile_s = time.perf_counter() - t0
    flops = None
    try:
        cost = compiled.cost_analysis()
        if isinstance(cost, list):  # older jax returns [dict]
            cost = cost[0]
        flops = float(cost.get("flops", 0.0)) or None
    except Exception:
        pass
    for _ in range(WARMUP):
        out = compiled(*args)
    jax.block_until_ready(out)
    t0 = time.perf_counter()
    for _ in range(iters):
        out = compiled(*args)
    jax.block_until_ready(out)
    per_call = (time.perf_counter() - t0) / iters
    return compile_s, per_call, flops


def _make_batch(batch_size, n1, n2, n_pad, knn=20, geo=2, seed=0):
    from deepinteract_tpu.data.graph import stack_complexes
    from deepinteract_tpu.data.synthetic import random_complex

    rng = np.random.default_rng(seed)
    return stack_complexes(
        [
            random_complex(n1, n2, rng=rng, n_pad1=n_pad, n_pad2=n_pad, knn=knn,
                           geo_nbrhd_size=geo)
            for _ in range(batch_size)
        ]
    )


def main() -> None:
    import jax

    from deepinteract_tpu.models.model import DeepInteract, ModelConfig
    from deepinteract_tpu.training.optim import OptimConfig
    from deepinteract_tpu.training.steps import create_train_state, train_step

    dev = jax.devices()[0]
    _log(f"backend={dev.platform} device={dev.device_kind}")

    import dataclasses

    # DI_BENCH_DTYPE=bfloat16 measures the bf16 decoder activation path
    # (params/logits stay f32; see DecoderConfig.compute_dtype).
    bench_dtype = os.environ.get("DI_BENCH_DTYPE", "float32")
    if bench_dtype not in ("float32", "bfloat16"):
        raise SystemExit(
            f"DI_BENCH_DTYPE must be 'float32' or 'bfloat16', got {bench_dtype!r}"
        )
    base_cfg = ModelConfig(
        decoder=dataclasses.replace(
            ModelConfig().decoder, compute_dtype=bench_dtype
        )
    )
    model = DeepInteract(base_cfg)
    # The batch-8 train step exceeds a 16G v5e's HBM with full activation
    # storage; remat (decoder-block rematerialization) is the intended
    # config at that scale. Param trees are identical, so the same state
    # drives both models.
    model_remat = DeepInteract(
        dataclasses.replace(
            base_cfg,
            decoder=dataclasses.replace(base_cfg.decoder, remat=True),
        )
    )
    detail = {"backend": dev.platform, "device_kind": dev.device_kind,
              "iters": ITERS, "compute_dtype": bench_dtype, "buckets": {}}

    # (label, batch, n1, n2, pad, remat). Kept to two buckets: each
    # train-step compile costs minutes on the TPU and the driver runs on a
    # budget.
    scan_k = int(os.environ.get("DI_BENCH_SCAN", "8"))
    shapes = [
        ("b1_p128", 1, 100, 80, 128, False),
        ("b8_p128_remat", 8, 100, 80, 128, True),
    ]
    if os.environ.get("DI_BENCH_FAST"):
        shapes = shapes[:1]
    headline = None

    for label, bs, n1, n2, pad, remat in shapes:
        bench_model = model_remat if remat else model
        try:
            batch = _make_batch(bs, n1, n2, pad)
            state = create_train_state(
                bench_model, jax.tree_util.tree_map(lambda x: x[:1], batch),
                optim_cfg=OptimConfig(steps_per_epoch=100, num_epochs=50),
            )

            fwd = jax.jit(
                lambda params, bstats, b: bench_model.apply(
                    {"params": params, "batch_stats": bstats},
                    b.graph1, b.graph2, train=False,
                )
            )
            fc, fs, fflops = _time_compiled(
                fwd, (state.params, state.batch_stats, batch)
            )

            tstep = jax.jit(lambda s, b: train_step(s, b))
            tc, ts, tflops = _time_compiled(tstep, (state, batch))

            # Scanned path: K steps per dispatch. Host dispatch cost scales
            # with result-buffer count (~25 ms for the 3.4k-leaf state
            # through the TPU tunnel), so the scan amortizes it K-fold —
            # this is the throughput a real training run achieves
            # (Trainer steps_per_dispatch, training/steps.py). Guarded
            # separately: a scan-only failure (e.g. K stacked batches
            # overflowing HBM) must not discard the forward/train numbers
            # already measured above.
            from deepinteract_tpu.training.steps import (
                multi_train_step,
                stack_microbatches,
            )

            k = scan_k
            scan_error = None
            try:
                stacked = stack_microbatches([batch] * k)
                mstep = jax.jit(lambda s, bs: multi_train_step(s, bs))
                mc, ms, _ = _time_compiled(
                    mstep, (state, stacked), iters=max(ITERS // 4, 3)
                )
                scan_ms_per_step = ms * 1e3 / k
                scan_cps = bs * k / ms
            except Exception as exc:
                scan_error = str(exc).splitlines()[0][:300] if str(exc) else repr(exc)
                mc = ms = scan_ms_per_step = scan_cps = None
        except Exception as exc:  # one bucket failing must not kill the run
            msg = str(exc).splitlines()[0][:300] if str(exc) else repr(exc)
            detail["buckets"][label] = {"error": msg}
            _log(json.dumps({label: {"error": msg}}))
            if label == "b1_p128":
                # The stdout contract line must appear even when the
                # headline bucket fails: emit value 0 so the driver records
                # a failed measurement instead of an empty file.
                print(json.dumps({
                    "metric": f"train_complexes_per_sec_b1_p128_scan{scan_k}",
                    "value": 0.0, "unit": "complexes/s", "vs_baseline": 0.0,
                }), flush=True)
            continue

        entry = {
            "batch": bs, "pad": pad,
            "forward_ms": fs * 1e3, "forward_compile_s": fc,
            "forward_complexes_per_sec": bs / fs,
            "train_ms": ts * 1e3, "train_compile_s": tc,
            "train_complexes_per_sec": bs / ts,
        }
        if scan_error is None:
            entry.update({
                "train_scan_k": k,
                "train_scan_ms_per_step": scan_ms_per_step,
                "train_scan_complexes_per_sec": scan_cps,
                "train_scan_compile_s": mc,
            })
        else:
            entry["train_scan_error"] = scan_error
        if fflops:
            entry["forward_flops"] = fflops
            entry["forward_mfu"] = (fflops / fs) / PEAK_FLOPS
        if tflops:
            entry["train_flops"] = tflops
            entry["train_mfu"] = (tflops / ts) / PEAK_FLOPS
        detail["buckets"][label] = entry
        _log(json.dumps({label: entry}))
        if label == "b1_p128":
            headline = entry
            # Emit the contract line as soon as the headline bucket is done:
            # later buckets may exceed the driver's wall-clock budget on a
            # cold compile cache, and the stdout line must not be lost.
            # Headline = scanned train throughput (what a real training run
            # sustains); fall back to the per-dispatch single-step figure
            # if only the scan failed.
            if scan_error is None:
                value = headline["train_scan_complexes_per_sec"]
                metric = f"train_complexes_per_sec_b1_p128_scan{k}"
            else:
                value = headline["train_complexes_per_sec"]
                metric = "train_step_complexes_per_sec_b1_p128"
            print(json.dumps({
                "metric": metric,
                "value": round(value, 2),
                "unit": "complexes/s",
                "vs_baseline": round(value / CPU_BASELINE_COMPLEXES_PER_SEC, 2),
            }), flush=True)

    detail["cpu_baseline_complexes_per_sec"] = CPU_BASELINE_COMPLEXES_PER_SEC
    detail["peak_flops_assumed"] = PEAK_FLOPS
    # MFU figures divide XLA cost_analysis() flops by the assumed peak; the
    # cost model over-counts under rematerialization and aggressive fusion
    # (values > 1 are possible) — treat them as an upper-bound utilization
    # proxy, and complexes/sec as the ground truth.
    detail["mfu_note"] = "cost_analysis-based estimate; unreliable under remat"
    _log("DETAIL " + json.dumps(detail))


if __name__ == "__main__":
    main()
