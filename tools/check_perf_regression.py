"""Diff a fresh bench contract line against the committed trajectory.

The BENCH_r*.json artifacts record each round's bench capture, but
nothing ever *compared* consecutive rounds — a silent throughput cliff
(or the BENCH_r01/r05 ``"parsed": null`` plumbing failure, where the
run finished but the contract line was unparseable) only surfaced when
a human re-read the numbers. This tool makes the comparison a command::

    python bench.py | tee bench.log
    python tools/check_perf_regression.py --fresh bench.log

    # bless an intentional change as the new baseline
    python tools/check_perf_regression.py --fresh bench.log --update

Baseline resolution order: ``--baseline PATH`` > ``PERF_BASELINE.json``
(the blessed file ``--update`` writes) > the newest ``BENCH_r*.json``
whose contract is recoverable (its ``parsed`` field, else the final
JSON line of its ``tail`` capture).

Per-key tolerances are relative and direction-aware (throughput keys
regress only when they DROP; byte keys only when they GROW). A key the
baseline carried that the fresh contract lost is a plumbing regression
and fails loudly — that is the ``"parsed": null`` class generalized to
individual keys.

The FINAL stdout line is a machine-readable JSON contract
(tools/check_cli_contract.py, kind ``perf_regression``). Exit 0 = no
regression, 1 = regression or incomparable capture, 2 = usage error.
"""

from __future__ import annotations

import argparse
import glob
import json
import os
import re
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from tools.check_cli_contract import (  # noqa: E402
    check_cli_contract_text,
    final_json_line,
)

REPO_ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
BLESSED_BASENAME = "PERF_BASELINE.json"

# key -> (relative tolerance, direction): +1 keys are higher-better
# (regression = drop below baseline*(1-tol)); -1 keys are lower-better
# (regression = growth above baseline*(1+tol)). Only listed keys gate;
# everything else in the contract is provenance, not a perf number.
TOLERANCES = {
    "value": (0.30, +1),
    "vs_baseline": (0.30, +1),
    "analytic_train_mfu": (0.30, +1),
    "train_step_complexes_per_sec_b1_p128": (0.30, +1),
    "train_scan_complexes_per_sec_min_sample": (0.35, +1),
    "interaction_bytes": (0.05, -1),
    "screening.screen_pairs_per_sec": (0.35, +1),
    "screening.naive_pairs_per_sec": (0.35, +1),
    "screening.speedup_vs_naive": (0.35, +1),
    "screening.encode_reuse_ratio": (0.10, +1),
    # Proteome-index funnel contract (bench `screening.indexed`
    # subsection, ISSUE-17): ranked-partner throughput against a
    # prebuilt partitioned index (candidate pairs retired per second of
    # query wall — pre-filter reject or survivor decode) and the
    # end-to-end query latency an indexed /screen caller sees.
    # prefilter_survivor_frac is provenance (it is top_m/candidates by
    # construction), not gated.
    "screening.indexed.indexed_pairs_per_sec": (0.35, +1),
    "screening.indexed.query_p50_ms": (0.50, -1),
    "attribution.total_device_ms": (0.50, -1),
    # Overload-safety contract (bench `saturation` section, ISSUE-11):
    # the p99 ratio is the bounded-queue promise (lower = tighter tail
    # under oversubscription); served throughput under overload must not
    # collapse. Counts/rates are provenance, not gated.
    "saturation.p99_ratio": (0.50, -1),
    "saturation.served_per_sec": (0.35, +1),
    "saturation.served_p99_ms": (0.50, -1),
    # Zero-downtime rollover contract (bench `rollover` section,
    # ISSUE-13): the tail during a live weights rollover must stay
    # bounded, and dropped requests must stay at ZERO (see
    # ZERO_BASELINE_CEILINGS — a 0 baseline still gates).
    "rollover.p99_during_rollover_ms": (0.75, -1),
    # The ISSUE-13 acceptance bar is the RATIO (p99 during rollover vs
    # the same router's steady p99) — gate it directly, like
    # saturation.p99_ratio, so a fleet-layer tail regression can't hide
    # inside the absolute key's band when steady state shifted too.
    "rollover.p99_ratio": (0.50, -1),
    "rollover.dropped_requests": (0.0, -1),
    # Self-healing training contract (bench `recovery` section,
    # ISSUE-14): kill-to-first-resumed-step MTTR under the supervisor's
    # watchdog+restart path (CPU rehearsal — dominated by child respawn
    # + compile-cache-warm restore, so the wide band absorbs machine
    # noise, not capability loss), and steps re-executed after a
    # kill -9, whose bar is the --save_every_steps cadence. A zero
    # steps_reexecuted baseline still gates (ZERO_BASELINE_CEILINGS):
    # re-paying more than one cadence of work means the cursor or the
    # mid/ checkpoint stopped landing.
    "recovery.mttr_s": (1.00, -1),
    "recovery.steps_reexecuted": (0.0, -1),
    # Elastic-fleet contract (bench `elasticity` section, ISSUE-16): the
    # burst-phase tail over the steady baseline while the autoscaler
    # scales up, scales down, and absorbs a preemption — gate the RATIO
    # (like rollover.p99_ratio) so a fleet-layer tail regression can't
    # hide behind a shifted steady state. Dropped requests across the
    # whole diurnal trace have a ZERO bar (ZERO_BASELINE_CEILINGS):
    # elasticity must never shed correct traffic.
    "elasticity.p99_ratio": (0.50, -1),
    "elasticity.dropped_requests": (0.0, -1),
    # Input-pipeline contract (bench `input_pipeline` section, ISSUE-15):
    # prefetch_overlap_ratio is the stepped-loader rate with placement
    # double-buffered on the prefetch thread over the inline-placement
    # rate under scanned dispatch — the overlap must keep paying for
    # itself; the absolute prefetch-on scanned rate rides along.
    "input_pipeline.prefetch_overlap_ratio": (0.25, +1),
    "input_pipeline.scan_prefetch_cps": (0.35, +1),
    # Mesh-sharded serving contract (bench `mesh_serving` section,
    # ISSUE-20): the data-parallel mixed-traffic throughput ratio over a
    # single chip (higher-is-better, wide band — on the CPU rehearsal the
    # virtual mesh shares one core, so the ratio mostly tracks
    # coordination overhead) and the pair-sharded p512 single-complex
    # latency (lower-is-better).
    "mesh_serving.throughput_ratio": (0.30, +1),
    "mesh_serving.p512_latency_ms": (0.50, -1),
    # Assembly contract (bench `assembly` section, ISSUE-19): k-chain
    # complex scoring throughput (C(k,2) pairs through the encode-once
    # + micro-batched-decode path), and the encode-once invariant
    # itself — unique_encodes must never exceed the chain count k (see
    # ZERO_BASELINE_CEILINGS/DYNAMIC_CEILINGS: the measurement names its
    # own bar via assembly.chains), because any growth means a pair
    # re-encoded a chain and the O(k) encode economy silently became
    # O(k^2).
    "assembly.pairs_per_sec": (0.35, +1),
    "assembly.unique_encodes": (0.0, -1),
    # Sustained-training contract (tools/sustained_train.py sustained/v1,
    # ISSUE-15): sustained/micro-bench-scan ratio, the ROADMAP item 4
    # >=0.70 bar. Dormant until a blessed baseline carries the key (the
    # bless happens on hardware — the ratio is workload-shaped); once
    # present it gates like every other throughput ratio.
    "sustained.ratio_vs_scan": (0.25, +1),
}
# Lower-better keys whose baseline is legitimately 0 (e.g. dropped
# requests): relative tolerance math is undefined at 0, so these gate as
# an absolute ceiling — fresh must stay <= baseline + ceiling.
ZERO_BASELINE_CEILINGS = {
    "rollover.dropped_requests": 0.0,
    "elasticity.dropped_requests": 0.0,
    # The bench recovery section kills within one save cadence of the
    # last mid-epoch checkpoint, so even a 0-baseline round must keep
    # re-executed work under that cadence (2.0 is the section default;
    # see DYNAMIC_CEILINGS for the contract-driven override).
    "recovery.steps_reexecuted": 2.0,
    # Encode-once invariant: even against a 0-encode baseline (fully
    # cache-warm round), a fresh run must not exceed one encode per
    # chain (6.0 = the bench section's default k; see DYNAMIC_CEILINGS —
    # the contract's own assembly.chains overrides).
    "assembly.unique_encodes": 6.0,
}
# Ceilings whose true bound rides the contract itself: key -> the
# contract key holding it. The bench recovery cadence is operator-
# configurable (DI_BENCH_RECOVERY_CADENCE), and gating a 4-step-cadence
# run against a hardcoded 2 would manufacture phantom regressions (or
# mask real ones at cadence 1) — the measurement names its own bar.
DYNAMIC_CEILINGS = {
    "recovery.steps_reexecuted": "recovery.save_every_steps",
    "assembly.unique_encodes": "assembly.chains",
}
# Keys whose values must match exactly for the runs to be comparable at
# all (a different metric/unit is a different experiment, not a drift).
IDENTITY_KEYS = ("metric", "unit")

# When either side's contract carries a ``timing_warning`` (the shared
# timing core flagged unstable differenced samples — linearity outside
# the healthy band or reps disagreeing, tuning/timing.py), the headline
# throughput keys were measured under a degraded protocol: widen their
# tolerance instead of failing (or passing) on noise. Only the keys that
# derive from the warned measurement widen; byte/attribution keys keep
# their tolerance.
TIMING_WARNED_KEYS = frozenset({
    "value",
    "vs_baseline",
    "analytic_train_mfu",
    "train_step_complexes_per_sec_b1_p128",
    "train_scan_complexes_per_sec_min_sample",
})
TIMING_WARNED_FACTOR = 2.0


def _flatten(record: dict, prefix: str = "") -> dict:
    """One level of nesting ("screening.screen_pairs_per_sec") is enough
    for the contract's shape."""
    flat = {}
    for key, val in record.items():
        name = f"{prefix}{key}"
        if isinstance(val, dict):
            flat.update(_flatten(val, prefix=f"{name}."))
        else:
            flat[name] = val
    return flat


def recover_contract(path: str) -> dict:
    """A baseline file -> its bench contract dict. Accepts a blessed
    contract (``--update`` output), a driver BENCH_r capture (``parsed``
    field, else the final JSON line of ``tail``), or a raw stdout log."""
    with open(path) as fh:
        text = fh.read()
    try:
        blob = json.loads(text)
    except json.JSONDecodeError:
        return check_cli_contract_text(text, "bench")  # raw capture log
    if isinstance(blob, dict) and "metric" in blob and "value" in blob:
        return blob  # blessed contract
    if isinstance(blob, dict) and "tail" in blob:
        if isinstance(blob.get("parsed"), dict):
            return blob["parsed"]
        return check_cli_contract_text(blob["tail"], "bench")
    raise ValueError(f"{path}: not a bench contract, capture, or "
                     "BENCH_r artifact")


def resolve_baseline(explicit: str = "", root: str = ""):
    """(contract, path, notes) per the resolution order in the module
    doc. A corrupt/truncated blessed ``PERF_BASELINE.json`` DEGRADES to
    trajectory recovery (newest recoverable ``BENCH_r*.json``) with a
    loud note that rides into the final contract line — the gate keeps
    gating instead of crashing on a torn bless (the durable-artifacts
    discipline, ISSUE-12). An explicit ``--baseline`` still raises: the
    operator asked for THAT file."""
    root = root or REPO_ROOT  # read at call time (tests repoint it)
    notes = []
    if explicit:
        return recover_contract(explicit), explicit, notes
    blessed = os.path.join(root, BLESSED_BASENAME)
    if os.path.exists(blessed):
        try:
            return recover_contract(blessed), blessed, notes
        except (ValueError, json.JSONDecodeError) as exc:
            notes.append(
                f"BASELINE DEGRADED: blessed {BLESSED_BASENAME} is "
                f"corrupt/unreadable ({exc}) — falling back to the "
                "BENCH_r trajectory; re-bless with --update")
            print(f"WARNING: {notes[-1]}", file=sys.stderr)
    candidates = sorted(
        glob.glob(os.path.join(root, "BENCH_r*.json")),
        key=lambda p: int(re.search(r"r(\d+)", os.path.basename(p)).group(1)),
        reverse=True)
    errors = []
    for path in candidates:
        try:
            return recover_contract(path), path, notes
        except (ValueError, json.JSONDecodeError) as exc:
            errors.append(f"{os.path.basename(path)}: {exc}")
    raise FileNotFoundError(
        "no usable baseline: no --baseline, no readable "
        f"{BLESSED_BASENAME}, and no BENCH_r*.json with a recoverable "
        f"contract ({'; '.join(errors) or 'none found'})")


def compare(fresh: dict, baseline: dict) -> dict:
    """The diff verdict: regressions / improvements / missing keys."""
    flat_fresh = _flatten(fresh)
    flat_base = _flatten(baseline)
    regressions, improvements, missing, compared = [], [], [], []
    for key in IDENTITY_KEYS:
        if key in flat_base and flat_fresh.get(key) != flat_base[key]:
            regressions.append({
                "key": key, "kind": "identity",
                "baseline": flat_base[key], "fresh": flat_fresh.get(key),
                "detail": "contract identity changed — runs are not "
                          "comparable (use --update to bless)",
            })
    warned = bool(flat_fresh.get("timing_warning")
                  or flat_base.get("timing_warning"))
    for key, (tol, direction) in TOLERANCES.items():
        if key not in flat_base:
            continue
        base_val = flat_base[key]
        if not isinstance(base_val, (int, float)) or isinstance(
                base_val, bool):
            continue
        if key not in flat_fresh or not isinstance(
                flat_fresh[key], (int, float)) or isinstance(
                flat_fresh[key], bool):
            missing.append(key)
            continue
        new_val = float(flat_fresh[key])
        compared.append(key)
        if base_val == 0:
            ceiling = ZERO_BASELINE_CEILINGS.get(key)
            dyn_key = DYNAMIC_CEILINGS.get(key)
            if dyn_key is not None:
                dyn = flat_fresh.get(dyn_key)
                if (isinstance(dyn, (int, float))
                        and not isinstance(dyn, bool) and dyn > 0):
                    ceiling = float(dyn)
            if ceiling is not None and new_val > ceiling:
                regressions.append({
                    "key": key, "kind": "perf", "baseline": base_val,
                    "fresh": new_val, "tolerance": ceiling,
                    "detail": ("zero-baseline key exceeded its absolute "
                               f"ceiling ({ceiling})"),
                })
            continue
        widened = warned and key in TIMING_WARNED_KEYS
        if widened:
            tol = tol * TIMING_WARNED_FACTOR
        rel = (new_val - float(base_val)) / abs(float(base_val))
        worse = -rel if direction > 0 else rel
        entry = {"key": key, "baseline": base_val, "fresh": new_val,
                 "rel_change": round(rel, 4), "tolerance": tol}
        if widened:
            entry["tolerance_widened"] = (
                "timing_warning on the contract — unstable differenced "
                "samples (tuning/timing.py)")
        if worse > tol:
            regressions.append(dict(entry, kind="perf"))
        elif -worse > tol:
            improvements.append(entry)
    for key in missing:
        regressions.append({
            "key": key, "kind": "plumbing",
            "baseline": flat_base[key], "fresh": None,
            "detail": "baseline carried this perf key; the fresh "
                      "contract lost it (the \"parsed\": null class)",
        })
    notes = []
    if warned:
        notes.append(
            "timing_warning present on a contract — headline throughput "
            f"tolerances widened {TIMING_WARNED_FACTOR}x (unstable "
            "differenced samples; see tuning/timing.py)")
    if not compared and not regressions:
        notes.append("no overlapping perf keys with the baseline (old "
                     "artifact format?) — nothing was actually compared; "
                     "bless a fresh baseline with --update")
    if fresh.get("partial"):
        notes.append("fresh capture is partial (sections "
                     "skipped/errored) — absolute numbers may be thin")
    return {"regressions": regressions, "improvements": improvements,
            "compared": compared, "notes": notes,
            "ok": not regressions}


def main(argv=None) -> int:
    parser = argparse.ArgumentParser(description=__doc__)
    parser.add_argument("--fresh", default="-",
                        help="fresh bench stdout capture (file or '-')")
    parser.add_argument("--baseline", default="",
                        help="explicit baseline (blessed contract, "
                             "BENCH_r artifact, or capture log)")
    parser.add_argument("--update", action="store_true",
                        help="bless the fresh contract as the new "
                             "baseline (PERF_BASELINE.json)")
    parser.add_argument("--bless_to", default="",
                        help="where --update writes (default repo-root "
                             f"{BLESSED_BASENAME})")
    args = parser.parse_args(argv)

    if args.fresh == "-":
        text = sys.stdin.read()
    else:
        with open(args.fresh) as fh:
            text = fh.read()
    try:
        fresh = check_cli_contract_text(text, "bench")
    except ValueError as exc:
        print(f"PERF REGRESSION CHECK FAILED: fresh capture has no valid "
              f"bench contract line: {exc}", file=sys.stderr)
        print(json.dumps({
            "metric": "perf_regression", "value": 1.0, "unit": "regressions",
            "ok": False, "baseline": None, "compared": 0,
            "regressions": [{"key": "<contract>", "kind": "plumbing",
                             "detail": str(exc)}]}))
        return 1

    if args.update:
        out = args.bless_to or os.path.join(REPO_ROOT, BLESSED_BASENAME)
        tmp = out + ".tmp"
        with open(tmp, "w") as fh:
            json.dump(fresh, fh, indent=2)
            fh.write("\n")
        os.replace(tmp, out)
        print(f"blessed fresh contract -> {out}")
        print(json.dumps({
            "metric": "perf_regression", "value": 0.0, "unit": "regressions",
            "ok": True, "baseline": out, "compared": 0,
            "regressions": [], "blessed": True}))
        return 0

    try:
        baseline, baseline_path, resolve_notes = resolve_baseline(
            args.baseline)
    except (FileNotFoundError, ValueError) as exc:
        print(f"PERF REGRESSION CHECK FAILED: {exc}", file=sys.stderr)
        return 2

    verdict = compare(fresh, baseline)
    verdict["notes"] = resolve_notes + verdict["notes"]
    for reg in verdict["regressions"]:
        print(f"REGRESSION [{reg['kind']}] {reg['key']}: "
              f"{reg.get('baseline')} -> {reg.get('fresh')} "
              f"({reg.get('detail', reg.get('rel_change'))})",
              file=sys.stderr)
    for imp in verdict["improvements"]:
        print(f"improvement {imp['key']}: {imp['baseline']} -> "
              f"{imp['fresh']} ({imp['rel_change']:+.1%})")
    print(json.dumps({
        "metric": "perf_regression",
        "value": float(len(verdict["regressions"])),
        "unit": "regressions",
        "ok": verdict["ok"],
        "baseline": baseline_path,
        "compared": len(verdict["compared"]),
        "regressions": verdict["regressions"],
        "improvements": verdict["improvements"],
        "notes": verdict["notes"],
        "baseline_degraded": bool(resolve_notes),
    }))
    return 0 if verdict["ok"] else 1


if __name__ == "__main__":
    sys.exit(main())
