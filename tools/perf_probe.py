"""TPU timing cross-validation probe.

Three independent ways to time the flagship forward/train step, to
establish which protocols are trustworthy through the axon tunnel:

1. ``differenced``  — bench.py's protocol: (t_2k - t_k) / k with a host
   fetch per run. Reported at several k to expose nonlinearity.
2. ``device-loop``  — a lax.scan of K data-dependent iterations inside ONE
   executable: per-iter = total/K. Immune to dispatch/fetch overhead by
   construction (the loop lives on the device), at the cost of measuring
   the scanned variant of the computation.
3. ``fetch-cost``   — the host fetch alone, to size the fixed overhead.

Also A/Bs the scanned-chunk decoder vs the unrolled decoder to separate
"timing was wrong" from "the scan rewrite changed runtime".

Usage: python tools/perf_probe.py [pad] (default 128)
"""

from __future__ import annotations

import dataclasses
import sys
import time

import numpy as np


def main() -> int:
    import jax
    import jax.numpy as jnp

    sys.path.insert(0, ".")
    from bench import _make_batch, _time_compiled

    from deepinteract_tpu.models.model import DeepInteract, ModelConfig

    pad = int(sys.argv[1]) if len(sys.argv) > 1 else 128
    n1, n2 = (100, 80) if pad == 128 else (230, 200)
    dev = jax.devices()[0]
    print(f"device={dev.device_kind} pad={pad}", flush=True)

    batch = _make_batch(1, n1, n2, pad)

    def make(scan_chunks):
        base = ModelConfig()
        return DeepInteract(dataclasses.replace(
            base, decoder=dataclasses.replace(base.decoder,
                                              scan_chunks=scan_chunks)))

    results = {}
    for name, scan_chunks in (("scanned", True), ("unrolled", False)):
        model = make(scan_chunks)
        variables = model.init(jax.random.PRNGKey(0), batch.graph1,
                               batch.graph2, train=False)
        params, bstats = variables["params"], variables.get("batch_stats", {})

        fwd = jax.jit(lambda p, bs, b: model.apply(
            {"params": p, "batch_stats": bs}, b.graph1, b.graph2, train=False))

        # Protocol 1: differenced at k = 2, 4, 8.
        t0 = time.perf_counter()
        compiled = fwd.lower(params, bstats, batch).compile()
        compile_s = time.perf_counter() - t0
        print(f"[{name}] forward compile {compile_s:.1f}s", flush=True)
        for k in (2, 4, 8):
            _, timing, _ = _time_compiled(fwd, (params, bstats, batch),
                                          iters=k * 3, reps=3)
            print(f"[{name}] differenced k={timing['calls_per_sample']}: "
                  f"median {timing['median']*1e3:.3f} ms  "
                  f"min {timing['min']*1e3:.3f}  "
                  f"overhead {timing['overhead_ms']:.1f} ms  "
                  f"linearity {timing['linearity']:.3f}", flush=True)
            results[f"{name}_diff_k{k}"] = timing["median"]

        # Protocol 2: device-side loop, K iterations chained through a
        # carried accumulator and a per-iteration input perturbation.
        K = 32

        def looped(p, bs, b):
            def body(acc, i):
                g1 = dataclasses.replace(
                    b.graph1,
                    node_feats=b.graph1.node_feats + (i * 1e-6 + acc * 1e-20))
                out = model.apply({"params": p, "batch_stats": bs},
                                  g1, b.graph2, train=False)
                return acc + jnp.sum(out) * 1e-6, None

            acc, _ = jax.lax.scan(body, jnp.float32(0.0),
                                  jnp.arange(K, dtype=jnp.float32))
            return acc

        jloop = jax.jit(looped)
        t0 = time.perf_counter()
        cl = jloop.lower(params, bstats, batch).compile()
        print(f"[{name}] device-loop compile {time.perf_counter()-t0:.1f}s",
              flush=True)
        out = cl(params, bstats, batch)
        float(jax.device_get(out))  # warm
        samples = []
        for _ in range(3):
            t0 = time.perf_counter()
            out = cl(params, bstats, batch)
            float(jax.device_get(out))
            samples.append((time.perf_counter() - t0) / K)
        per_iter = float(np.median(samples))
        print(f"[{name}] device-loop K={K}: {per_iter*1e3:.3f} ms/iter",
              flush=True)
        results[f"{name}_loop"] = per_iter

    # Protocol 3: fetch cost alone (small scalar vs the full logits).
    model = make(True)
    variables = model.init(jax.random.PRNGKey(0), batch.graph1, batch.graph2,
                           train=False)
    fwd = jax.jit(lambda p, bs, b: model.apply(
        {"params": p, "batch_stats": bs}, b.graph1, b.graph2, train=False))
    out = fwd(variables["params"], variables.get("batch_stats", {}), batch)
    jax.block_until_ready(out)
    for label, fetch in (
        ("device_get(logits)", lambda: np.asarray(jax.device_get(out))),
        ("block_until_ready", lambda: jax.block_until_ready(out)),
    ):
        t0 = time.perf_counter()
        for _ in range(5):
            fetch()
        print(f"fetch {label}: {(time.perf_counter()-t0)/5*1e3:.1f} ms",
              flush=True)

    print("RESULTS " + str({k: round(v * 1e3, 3) for k, v in results.items()}),
          flush=True)
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
