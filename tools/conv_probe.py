"""Conv-path characterization for the decoder (device-loop timing).

tools/decoder_ablation.py shows the bare conv skeleton runs ~5.8 ms at
p128 (~19 TFLOP/s on a 197 TFLOP/s chip). This probe isolates why:

  dilated-f32    — the real cycle (dilations 1,2,4,8), f32
  d1-f32         — same convs, all dilation 1 (is dilated lowering slow?)
  dilated-bf16   — bf16 activations AND conv compute
  wide-f32       — 3x3 at full 128 channels, no bottleneck (MXU packing)
  conv3x3-x56    — 56 plain 3x3 convs at 64ch (per-op floor)
"""

from __future__ import annotations

import sys
import time

import numpy as np

K = 32


def device_loop_time(apply_fn, variables, x):
    import jax
    import jax.numpy as jnp

    def looped(v, x):
        def body(acc, i):
            out = apply_fn(v, x + (i * 1e-6 + acc * 1e-20))
            return acc + jnp.sum(out).astype(jnp.float32) * 1e-6, None

        acc, _ = jax.lax.scan(body, jnp.float32(0.0),
                              jnp.arange(K, dtype=jnp.float32))
        return acc

    jloop = jax.jit(looped)
    cl = jloop.lower(variables, x).compile()
    out = cl(variables, x)
    float(jax.device_get(out))
    samples = []
    for _ in range(3):
        t0 = time.perf_counter()
        out = cl(variables, x)
        float(jax.device_get(out))
        samples.append((time.perf_counter() - t0) / K)
    return float(np.median(samples))


def main() -> int:
    import jax
    import jax.numpy as jnp
    from flax import linen as nn

    pad = int(sys.argv[1]) if len(sys.argv) > 1 else 128
    print(f"device={jax.devices()[0].device_kind} pad={pad} K={K}", flush=True)
    rng = np.random.default_rng(0)

    def make_stack(cycle, dtype, mid, kernel=3):
        class Chunk(nn.Module):
            @nn.compact
            def __call__(self, hh):
                for d in cycle:
                    r = hh
                    hh = nn.Conv(mid, (1, 1), dtype=dtype)(nn.elu(hh))
                    hh = nn.Conv(mid, (kernel, kernel), kernel_dilation=(d, d),
                                 padding=d if kernel == 3 else 0,
                                 dtype=dtype)(nn.elu(hh))
                    hh = nn.Conv(128, (1, 1), dtype=dtype)(nn.elu(hh))
                    hh = hh + r
                return hh, None

        class Stack(nn.Module):
            @nn.compact
            def __call__(self, t):
                scan = nn.scan(Chunk, variable_axes={"params": 0},
                               split_rngs={"params": True}, length=14)
                h, _ = scan(name="chunks")(t.astype(dtype))
                return h.astype(jnp.float32)

        return Stack()

    x = jnp.asarray(rng.standard_normal((1, pad, pad, 128)).astype(np.float32))

    for name, module in (
        ("dilated-f32", make_stack((1, 2, 4, 8), jnp.float32, 64)),
        ("d1-f32", make_stack((1, 1, 1, 1), jnp.float32, 64)),
        ("dilated-bf16", make_stack((1, 2, 4, 8), jnp.bfloat16, 64)),
        ("wide-f32", make_stack((1, 2, 4, 8), jnp.float32, 128)),
    ):
        variables = module.init(jax.random.PRNGKey(0), x)
        t = device_loop_time(lambda v, xx: module.apply(v, xx), variables, x)
        print(f"{name:14s} {t*1e3:8.3f} ms/iter", flush=True)

    class Plain3x3(nn.Module):
        @nn.compact
        def __call__(self, t):
            h = t[..., :64]

            class One(nn.Module):
                @nn.compact
                def __call__(self, hh):
                    return nn.Conv(64, (3, 3), padding=1)(hh), None

            scan = nn.scan(One, variable_axes={"params": 0},
                           split_rngs={"params": True}, length=56)
            h, _ = scan(name="convs")(h)
            return h

    module = Plain3x3()
    variables = module.init(jax.random.PRNGKey(0), x)
    t = device_loop_time(lambda v, xx: module.apply(v, xx), variables, x)
    gflop = 56 * 2 * 9 * 64 * 64 * pad * pad / 1e9
    print(f"conv3x3-x56    {t*1e3:8.3f} ms/iter  "
          f"({gflop / t / 1e3:.1f} TFLOP/s)", flush=True)
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
