"""Decoder cost bisection on the TPU (device-loop timing).

The flagship forward at p128 runs ~12 ms (tools/perf_probe.py) — analytic
MFU ~0.05 — with ~95% of FLOPs in the decoder convs. This probe times the
decoder IN ISOLATION on a fixed [1, P, P, 256] pair tensor and ablates one
suspect at a time to find where the wall-clock actually goes:

  full        — InteractionDecoder as configured (inorm + SE + mask, f32)
  no-mask     — mask=None (drops mask multiplies + masked statistics)
  no-inorm    — use_inorm=False in the base ResNet (phase2-style blocks)
  no-se       — SE gates removed
  convs-only  — no inorm, no SE, no mask: the bare conv stack
  bf16        — full, compute_dtype=bfloat16
  gt-only     — the full model MINUS decoder (encoder cost cross-check)

Each variant is timed with a K-iteration lax.scan device loop (per-iter =
total/K), the only protocol the axon tunnel cannot distort.
"""

from __future__ import annotations

import dataclasses
import sys
import time

import numpy as np

K = 32


def device_loop_time(apply_fn, variables, x, mask):
    import jax
    import jax.numpy as jnp

    def looped(v, x, mask):
        def body(acc, i):
            out = apply_fn(v, x + (i * 1e-6 + acc * 1e-20), mask)
            return acc + jnp.sum(out) * 1e-6, None

        acc, _ = jax.lax.scan(body, jnp.float32(0.0),
                              jnp.arange(K, dtype=jnp.float32))
        return acc

    jloop = jax.jit(looped)
    t0 = time.perf_counter()
    cl = jloop.lower(variables, x, mask).compile()
    compile_s = time.perf_counter() - t0
    out = cl(variables, x, mask)
    float(jax.device_get(out))
    samples = []
    for _ in range(3):
        t0 = time.perf_counter()
        out = cl(variables, x, mask)
        float(jax.device_get(out))
        samples.append((time.perf_counter() - t0) / K)
    return float(np.median(samples)), compile_s


def main() -> int:
    import jax
    import jax.numpy as jnp
    from flax import linen as nn

    sys.path.insert(0, ".")
    from deepinteract_tpu.models.decoder import (
        DecoderConfig,
        DilatedResNet,
        InteractionDecoder,
        InstanceNorm,
        SEBlock,
    )

    pad = int(sys.argv[1]) if len(sys.argv) > 1 else 128
    dev = jax.devices()[0]
    print(f"device={dev.device_kind} pad={pad} K={K}", flush=True)

    rng = np.random.default_rng(0)
    x = jnp.asarray(rng.standard_normal((1, pad, pad, 256)).astype(np.float32))
    mask_np = np.zeros((1, pad, pad), bool)
    mask_np[:, : pad - 20, : pad - 28] = True
    mask = jnp.asarray(mask_np)

    results = {}

    def run(name, module, use_mask=True):
        m = mask if use_mask else None
        variables = module.init(jax.random.PRNGKey(0), x, m)
        per_iter, compile_s = device_loop_time(
            lambda v, xx, mm: module.apply(v, xx, mm), variables, x, m)
        results[name] = per_iter
        print(f"{name:12s} {per_iter*1e3:8.3f} ms/iter  (compile {compile_s:.0f}s)",
              flush=True)

    base = DecoderConfig()  # 14 chunks, 128 ch, scan_chunks=True

    run("full", InteractionDecoder(base))
    run("no-mask", InteractionDecoder(base), use_mask=False)
    run("bf16", InteractionDecoder(
        dataclasses.replace(base, compute_dtype="bfloat16")))

    class StrippedDecoder(nn.Module):
        """base ResNet with ablations (mirrors InteractionDecoder's base
        stage, which holds 56 of the 62 blocks)."""

        use_inorm: bool = True
        use_se: bool = True

        @nn.compact
        def __call__(self, t, m=None):
            h = nn.Conv(128, (1, 1), name="conv2d_1")(t)
            if self.use_inorm:
                h = nn.elu(InstanceNorm(128, name="inorm_1")(h, m))
            resnet = DilatedResNet(
                128, 14, (1, 2, 4, 8), use_inorm=self.use_inorm,
                initial_projection=True, scan_chunks=True, name="base")
            if not self.use_se:
                # monkey-level ablation: SEBlock with identity behavior is
                # not expressible via config; emulate by zero-size? Instead
                # time the resnet as-is minus inorm separately; see no-se2.
                pass
            h, _ = resnet(h, m)
            h = nn.elu(h)
            return nn.Conv(2, (1, 1), name="head")(h)

    run("no-inorm", StrippedDecoder(use_inorm=False))

    class ConvsOnly(nn.Module):
        """Bare conv skeleton of one 14-chunk base ResNet (no norm/SE/mask):
        the MXU-only lower bound."""

        @nn.compact
        def __call__(self, t, m=None):
            h = nn.Conv(128, (1, 1))(t)

            class Chunk(nn.Module):
                @nn.compact
                def __call__(self, hh, mm=None):
                    for d in (1, 2, 4, 8):
                        r = hh
                        hh = nn.Conv(64, (1, 1))(nn.elu(hh))
                        hh = nn.Conv(64, (3, 3), kernel_dilation=(d, d),
                                     padding=d)(nn.elu(hh))
                        hh = nn.Conv(128, (1, 1))(nn.elu(hh))
                        hh = hh + r
                    return hh, None

            scan = nn.scan(Chunk, variable_axes={"params": 0},
                           split_rngs={"params": True}, length=14,
                           in_axes=nn.broadcast)
            h, _ = scan(name="chunks")(h, m)
            return nn.Conv(2, (1, 1))(h)

    run("convs-only", ConvsOnly(), use_mask=False)

    # SE cost = full - (inorm cost) - ... : direct variant with SE stripped
    # by zeroing? Approximate SE cost as full - no_se where no_se reuses the
    # stripped decoder WITH inorm but the DilatedResNet's SE intact is the
    # full path; instead measure SE alone on the activation shape:
    class SEOnly(nn.Module):
        @nn.compact
        def __call__(self, t, m=None):
            h = t[..., :128]
            for i in range(56):
                h = SEBlock(128, name=f"se_{i}")(h, m)
            return h

    run("se-x56", SEOnly())

    class InormOnly(nn.Module):
        @nn.compact
        def __call__(self, t, m=None):
            h = t[..., :128]
            for i in range(56):
                h = InstanceNorm(128, name=f"in_{i}")(h, m)
            return h

    run("inorm-x56", InormOnly())

    print("RESULTS " + str({k: round(v * 1e3, 3) for k, v in results.items()}),
          flush=True)
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
