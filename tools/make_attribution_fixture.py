"""Regenerate the checked-in attribution fixture (tests/golden/attribution/).

The fixture is one CPU-profiler capture of the REAL interaction decoder
(masked forward, three ``device_step``-annotated executions) plus the
artifacts the attribution tests reconcile against:

* ``host.trace.json.gz``       — the jax.profiler trace-event file (renamed
                            from the capture's ``plugins/profile/...``
                            layout; the parser accepts bare files);
* ``events.jsonl``        — the PR-3 span log written DURING the same
                            capture (the phase-wall cross-check source);
* ``census.json``         — ``{"census": {...}, "meta": {...}}`` from
                            the same compiled executable's HLO entry
                            computation (obs/hloquery.py).

Tests only parse these files — regeneration (this script) is the only
step that needs a compile. Deterministic inputs; the timings inside are
whatever this machine measured, and tests assert structure + internal
consistency, never absolute times.

Usage: JAX_PLATFORMS=cpu python tools/make_attribution_fixture.py
"""

from __future__ import annotations

import glob
import gzip
import json
import os
import shutil
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

OUT_DIR = os.path.join(os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))), "tests", "golden", "attribution")
PAD = 48
STEPS = 3


def main() -> int:
    import jax
    import jax.numpy as jnp
    import numpy as np

    from deepinteract_tpu.models.decoder import DecoderConfig, InteractionDecoder
    from deepinteract_tpu.obs import device as obs_device
    from deepinteract_tpu.obs import hloquery
    from deepinteract_tpu.obs import spans as obs_spans

    os.makedirs(OUT_DIR, exist_ok=True)
    events_path = os.path.join(OUT_DIR, "events.jsonl")
    if os.path.exists(events_path):
        os.unlink(events_path)
    obs_spans.configure(events_path)

    rng = np.random.default_rng(0)
    # 4 chunks / 32 channels: the same masked bottleneck structure (and
    # the same re-mask select chain) as the flagship 14-chunk decoder at
    # a fraction of the trace size — the fixture is checked into git.
    cfg = DecoderConfig(num_chunks=4, num_channels=32)
    x = jnp.asarray(
        rng.standard_normal((1, PAD, PAD, cfg.in_channels)).astype(np.float32))
    mask_np = np.zeros((1, PAD, PAD), bool)
    mask_np[:, : PAD - 8, : PAD - 12] = True
    mask = jnp.asarray(mask_np)
    model = InteractionDecoder(cfg)
    variables = model.init(jax.random.PRNGKey(0), x, mask)
    compiled = jax.jit(
        lambda v, xx: model.apply(v, xx, mask)
    ).lower(variables, x).compile()
    compiled(variables, x)[0].block_until_ready()  # warm outside capture

    capture_dir = os.path.join(OUT_DIR, "_capture")
    shutil.rmtree(capture_dir, ignore_errors=True)
    with obs_device.capture(capture_dir):
        for i in range(STEPS):
            with obs_spans.span("device_step", step_num=i):
                np.asarray(compiled(variables, x))
    obs_spans.close()

    files = glob.glob(os.path.join(capture_dir, "**", "*.trace.json*"),
                      recursive=True)
    assert files, "capture produced no trace file"
    src = files[0]
    dst = os.path.join(OUT_DIR, "host.trace.json.gz")
    if src.endswith(".gz"):
        shutil.copyfile(src, dst)
    else:
        with open(src, "rb") as fin, gzip.open(dst, "wb") as fout:
            shutil.copyfileobj(fin, fout)
    shutil.rmtree(capture_dir, ignore_errors=True)

    census = hloquery.census_compiled(compiled)
    meta = {
        "device": jax.devices()[0].device_kind,
        "platform": jax.devices()[0].platform,
        "pad": PAD, "masked": True, "steps": STEPS,
        "source": "decoder_forward_fixture",
        "jax_version": jax.__version__,
    }
    with open(os.path.join(OUT_DIR, "census.json"), "w") as fh:
        json.dump({"census": dict(census), "meta": meta}, fh, indent=2,
                  sort_keys=True)

    trace = obs_device.load_profile(dst, phase_names=("device_step",))
    print(f"fixture written to {OUT_DIR}: {len(trace.ops)} op events, "
          f"{len(trace.phases)} device_step windows, "
          f"{sum(census.values())} census launches")
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
