"""Third probe: K-differenced device loops (the fully-corrected protocol).

Per-iteration time = (t_K2 - t_K1) / (K2 - K1) with the loop length a
runtime-switchable bound... lax.scan length is static, so compile TWO
loops (K1=8, K2=40) per op and difference their wall times. This removes
BOTH the host dispatch/fetch overhead AND any fixed per-dispatch cost
that polluted the K=32 single-loop numbers.
"""

from __future__ import annotations

import sys
import time

import numpy as np

K1, K2 = 8, 40


def diff_time(make_looped, *args):
    import jax

    def t_for(k):
        cl = jax.jit(make_looped(k)).lower(*args).compile()
        out = cl(*args)
        float(jax.device_get(out))
        samples = []
        for _ in range(5):
            t0 = time.perf_counter()
            out = cl(*args)
            float(jax.device_get(out))
            samples.append(time.perf_counter() - t0)
        return float(np.median(samples))

    t1, t2 = t_for(K1), t_for(K2)
    return (t2 - t1) / (K2 - K1), t1, t2


def op_loop(fn):
    import jax.numpy as jnp
    from jax import lax

    def make(k):
        def looped(*a):
            def body(acc, i):
                out = fn(*a, acc, i)
                return acc + jnp.sum(out).astype(jnp.float32) * 1e-30, None

            acc, _ = lax.scan(body, jnp.float32(0.0),
                              jnp.arange(k, dtype=jnp.float32))
            return acc

        return looped

    return make


def main() -> int:
    import jax
    import jax.numpy as jnp
    from jax import lax

    print(f"device={jax.devices()[0].device_kind} K1={K1} K2={K2}", flush=True)
    rng = np.random.default_rng(0)

    def report(name, fn, gflop, *args):
        per, t1, t2 = diff_time(op_loop(fn), *args)
        per = max(per, 1e-9)
        print(f"{name:34s} {per*1e6:9.1f} us/op ({gflop/per/1e3:7.1f} TFLOP/s)"
              f"  [t{K1}={t1*1e3:.1f}ms t{K2}={t2*1e3:.1f}ms]", flush=True)

    x = jnp.asarray(rng.standard_normal((1, 128, 128, 64)).astype(np.float32))
    w = jnp.asarray(rng.standard_normal((3, 3, 64, 64)).astype(np.float32) * 0.1)

    def conv(xx, ww, acc, i):
        return lax.conv_general_dilated(
            xx + acc * 1e-30 + i * 1e-9, ww, (1, 1), "SAME",
            dimension_numbers=("NHWC", "HWIO", "NHWC"))

    report("conv3x3 [1,128,128,64]", conv, 2 * 9 * 64 * 64 * 128 * 128 / 1e9,
           x, w)

    wb = jnp.asarray(rng.standard_normal((3, 3, 64, 64)).astype(np.float32) * 0.1)

    def conv_bf16(xx, ww, acc, i):
        y = lax.conv_general_dilated(
            (xx + acc * 1e-30 + i * 1e-9).astype(jnp.bfloat16),
            ww.astype(jnp.bfloat16), (1, 1), "SAME",
            dimension_numbers=("NHWC", "HWIO", "NHWC"))
        return y.astype(jnp.float32)

    report("conv3x3 bf16", conv_bf16, 2 * 9 * 64 * 64 * 128 * 128 / 1e9, x, wb)

    a2 = jnp.asarray(rng.standard_normal((4096, 512)).astype(np.float32))
    b2 = jnp.asarray(rng.standard_normal((512, 512)).astype(np.float32))

    def mm(aa, bb, acc, i):
        return (aa + acc * 1e-30 + i * 1e-9) @ bb

    report("matmul [4096,512]x[512,512]", mm, 2 * 4096 * 512 * 512 / 1e9,
           a2, b2)

    x8 = jnp.asarray(rng.standard_normal((8, 128, 128, 64)).astype(np.float32))
    report("conv3x3 batch8", conv, 8 * 2 * 9 * 64 * 64 * 128 * 128 / 1e9,
           x8, w)

    # Elementwise pass: the memory-bandwidth yardstick (reads+writes 8MB).
    def ew(xx, acc, i):
        return xx * (1.0 + i * 1e-9) + acc * 1e-30

    report("elementwise [1,128,128,64]", ew, 0.004, x)  # ~GB moved, not GFLOP
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
