"""Second conv probe: conv lowering vs equivalent matmul, and precision.

conv3x3 at [1,128,128,64] measured 11 TFLOP/s (tools/conv_probe.py). Is
that the conv LOWERING or the MXU configuration? Compare:

  conv3x3 prec=DEFAULT / HIGHEST   — explicit precision
  matmul-eq                        — [16384,576]x[576,64] einsum, the same
                                     contraction as the conv's im2col
  matmul-sq                        — [4096,512]x[512,512] square control
  conv3x3-b8                       — batch 8 (amortize per-op overhead)
"""

from __future__ import annotations

import sys
import time

import numpy as np

K = 32


def loop_time(fn, *args):
    import jax
    import jax.numpy as jnp

    def looped(*a):
        def body(acc, i):
            out = fn(*a, acc, i)
            return acc + jnp.sum(out).astype(jnp.float32) * 1e-30, None

        acc, _ = jax.lax.scan(body, jnp.float32(0.0),
                              jnp.arange(K, dtype=jnp.float32))
        return acc

    cl = jax.jit(looped).lower(*args).compile()
    out = cl(*args)
    float(jax.device_get(out))
    samples = []
    for _ in range(3):
        t0 = time.perf_counter()
        out = cl(*args)
        float(jax.device_get(out))
        samples.append((time.perf_counter() - t0) / K)
    return float(np.median(samples))


def main() -> int:
    import jax
    import jax.numpy as jnp
    from jax import lax

    print(f"device={jax.devices()[0].device_kind} K={K}", flush=True)
    rng = np.random.default_rng(0)
    x = jnp.asarray(rng.standard_normal((1, 128, 128, 64)).astype(np.float32))
    w = jnp.asarray(rng.standard_normal((3, 3, 64, 64)).astype(np.float32) * 0.1)

    for prec in ("default", "highest"):
        def conv(xx, ww, acc, i, _p=prec):
            return lax.conv_general_dilated(
                xx + acc * 1e-30 + i * 1e-9, ww, (1, 1), "SAME",
                dimension_numbers=("NHWC", "HWIO", "NHWC"), precision=_p)

        t = loop_time(conv, x, w)
        gf = 2 * 9 * 64 * 64 * 128 * 128 / 1e9
        print(f"conv3x3 prec={prec:8s} {t*1e6:9.1f} us  ({gf/t/1e3:.1f} TFLOP/s)",
              flush=True)

    a = jnp.asarray(rng.standard_normal((16384, 576)).astype(np.float32))
    b = jnp.asarray(rng.standard_normal((576, 64)).astype(np.float32))

    def mm(aa, bb, acc, i):
        return (aa + acc * 1e-30 + i * 1e-9) @ bb

    t = loop_time(mm, a, b)
    gf = 2 * 16384 * 576 * 64 / 1e9
    print(f"matmul-eq [16384,576]x[576,64] {t*1e6:9.1f} us  "
          f"({gf/t/1e3:.1f} TFLOP/s)", flush=True)

    a2 = jnp.asarray(rng.standard_normal((4096, 512)).astype(np.float32))
    b2 = jnp.asarray(rng.standard_normal((512, 512)).astype(np.float32))
    t = loop_time(mm, a2, b2)
    gf = 2 * 4096 * 512 * 512 / 1e9
    print(f"matmul-sq [4096,512]x[512,512] {t*1e6:9.1f} us  "
          f"({gf/t/1e3:.1f} TFLOP/s)", flush=True)

    x8 = jnp.asarray(rng.standard_normal((8, 128, 128, 64)).astype(np.float32))

    def conv8(xx, ww, acc, i):
        return lax.conv_general_dilated(
            xx + acc * 1e-30 + i * 1e-9, ww, (1, 1), "SAME",
            dimension_numbers=("NHWC", "HWIO", "NHWC"))

    t = loop_time(conv8, x8, w)
    gf = 8 * 2 * 9 * 64 * 64 * 128 * 128 / 1e9
    print(f"conv3x3-b8            {t*1e6:9.1f} us  ({gf/t/1e3:.1f} TFLOP/s)",
          flush=True)
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
