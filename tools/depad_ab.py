"""K-differenced A/B of DecoderConfig.depad_stats on the full decoder."""

from __future__ import annotations

import dataclasses
import sys
import time

import numpy as np

K1, K2 = 8, 40


def diff_time(apply_fn, variables, x, mask):
    import jax
    import jax.numpy as jnp
    from jax import lax

    def make(k):
        def looped(v, xx, mm):
            def body(acc, i):
                out = apply_fn(v, xx + (i * 1e-6 + acc * 1e-20), mm)
                return acc + jnp.sum(out).astype(jnp.float32) * 1e-6, None

            acc, _ = lax.scan(body, jnp.float32(0.0),
                              jnp.arange(k, dtype=jnp.float32))
            return acc

        return looped

    def t_for(k):
        cl = jax.jit(make(k)).lower(variables, x, mask).compile()
        out = cl(variables, x, mask)
        float(jax.device_get(out))
        samples = []
        for _ in range(5):
            t0 = time.perf_counter()
            out = cl(variables, x, mask)
            float(jax.device_get(out))
            samples.append(time.perf_counter() - t0)
        return float(np.median(samples))

    t1, t2 = t_for(K1), t_for(K2)
    return (t2 - t1) / (K2 - K1)


def main() -> int:
    import jax
    import jax.numpy as jnp

    sys.path.insert(0, ".")
    from deepinteract_tpu.models.decoder import DecoderConfig, InteractionDecoder

    pad = int(sys.argv[1]) if len(sys.argv) > 1 else 128
    print(f"device={jax.devices()[0].device_kind} pad={pad}", flush=True)
    rng = np.random.default_rng(0)
    x = jnp.asarray(rng.standard_normal((1, pad, pad, 256)).astype(np.float32))
    mask_np = np.zeros((1, pad, pad), bool)
    mask_np[:, : pad - 20, : pad - 28] = True
    mask = jnp.asarray(mask_np)

    for label, kw in (
        ("depad-f32", dict(depad_stats=True)),
        ("masked-f32", dict(depad_stats=False)),
        ("depad-bf16", dict(depad_stats=True, compute_dtype="bfloat16")),
        ("masked-bf16", dict(depad_stats=False, compute_dtype="bfloat16")),
        ("nomask-f32", dict(depad_stats=False)),
    ):
        cfg = DecoderConfig(**kw)
        module = InteractionDecoder(cfg)
        m = None if label.startswith("nomask") else mask
        variables = module.init(jax.random.PRNGKey(0), x, m)
        t = diff_time(lambda v, xx, mm: module.apply(v, xx, mm), variables, x, m)
        print(f"{label:12s} {t*1e3:8.3f} ms/iter", flush=True)
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
