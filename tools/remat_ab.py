"""Scanned-train remat-policy A/B: full-block recompute vs conv-saving
policy vs no remat.

Full-block remat re-runs the decoder's convs in backward (~one extra
decoder forward of FLOPs, counted by bench.py's analytic_train_flops);
the 'convs' checkpoint policy (DecoderConfig.remat_policy) saves conv
outputs and recomputes only the elementwise chain, and no-remat saves
everything. Which one wins on the chip depends on whether the saved
recompute beats the extra HBM traffic of the larger residual set — this
tool measures all three on the same scanned-dispatch protocol as
tools/scan_ab.py (single-dispatch timings carry ±10-20% tunnel spread).
Variants that OOM are reported as such, not crashed on.

Usage: python tools/remat_ab.py [batch] [pad] [dtype]   (defaults 8 128
bfloat16 — the throughput flagship config)
"""

from __future__ import annotations

import dataclasses
import json
import os
import sys
import time

import numpy as np

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


def main() -> int:
    import jax

    from deepinteract_tpu.data.graph import stack_complexes
    from deepinteract_tpu.data.synthetic import random_complex
    from deepinteract_tpu.models.model import DeepInteract, ModelConfig
    from deepinteract_tpu.training.optim import OptimConfig
    from deepinteract_tpu.training.steps import (
        create_train_state,
        multi_train_step,
        stack_microbatches,
    )

    bs = int(sys.argv[1]) if len(sys.argv) > 1 else 8
    pad = int(sys.argv[2]) if len(sys.argv) > 2 else 128
    dtype = sys.argv[3] if len(sys.argv) > 3 else "bfloat16"
    scan_k = 8
    lengths = {128: (100, 80), 256: (230, 200), 384: (370, 350),
               512: (500, 470)}
    if pad not in lengths:
        raise SystemExit(f"unsupported pad {pad}; choose from "
                         f"{sorted(lengths)}")
    n1, n2 = lengths[pad]
    rng = np.random.default_rng(0)
    batch = stack_complexes([
        random_complex(n1, n2, rng=rng, n_pad1=pad, n_pad2=pad, knn=20,
                       geo_nbrhd_size=2)
        for _ in range(bs)
    ])
    print(f"device={jax.devices()[0].device_kind} b{bs} p{pad} {dtype} "
          f"scan{scan_k}", flush=True)

    variants = (("full", True, "full"), ("convs", True, "convs"),
                ("none", False, "full"))
    results = {}
    state_cache = {}
    for name, remat, policy in variants:
        base = ModelConfig()
        model = DeepInteract(dataclasses.replace(
            base,
            decoder=dataclasses.replace(base.decoder, remat=remat,
                                        remat_policy=policy,
                                        compute_dtype=dtype),
        ))
        if "state" not in state_cache:
            state_cache["state"] = create_train_state(
                model, jax.tree_util.tree_map(lambda x: x[:1], batch),
                optim_cfg=OptimConfig(steps_per_epoch=100, num_epochs=50))
        # Identical param tree across variants — swap only the apply_fn.
        state = state_cache["state"].replace(apply_fn=model.apply)
        stacked = stack_microbatches([batch] * scan_k)
        mstep = jax.jit(lambda s, bst: multi_train_step(s, bst))
        try:
            t0 = time.perf_counter()
            compiled = mstep.lower(state, stacked).compile()
            compile_s = time.perf_counter() - t0

            def run(ncalls):
                out = None
                t0 = time.perf_counter()
                for _ in range(ncalls):
                    out = compiled(state, stacked)
                jax.block_until_ready(out)
                # Forced host fetch: dispatch-only timing lies via the tunnel.
                float(np.asarray(jax.device_get(out[1]["loss"])).ravel()[0])
                return time.perf_counter() - t0

            run(1)  # warmup
            samples, clamped = [], 0
            for _ in range(3):
                t1, t2 = run(1), run(2)
                if t2 <= t1:  # differencing noise (same guard as bench.py)
                    clamped += 1
                    continue
                samples.append((t2 - t1) / scan_k)
        except Exception as exc:
            msg = str(exc).splitlines()[0][:300]
            results[name] = {"error": msg}
            print(f"{name}: FAILED — {msg}", flush=True)
            continue
        if not samples:
            results[name] = {"error": f"all {clamped} reps hit t2<=t1 "
                             "differencing noise; timing untrustworthy"}
            print(f"{name}: FAILED — timing degenerate", flush=True)
            continue
        per_step = float(np.median(samples))
        results[name] = {"per_step_ms": per_step * 1e3,
                         "complexes_per_sec": bs / per_step,
                         "compile_s": compile_s,
                         "clamped_samples": clamped}
        print(f"{name}: {per_step*1e3:.2f} ms/step "
              f"({bs/per_step:.1f} c/s, compile {compile_s:.0f}s)", flush=True)

    if "per_step_ms" in results.get("full", {}):
        for name in ("convs", "none"):
            if "per_step_ms" in results.get(name, {}):
                results[f"{name}_vs_full"] = (
                    results["full"]["per_step_ms"]
                    / results[name]["per_step_ms"])
    print("RESULT " + json.dumps(results))
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
