"""Static check: no bare ``print(`` in ``deepinteract_tpu/`` outside ``cli/``.

Thin shim over the framework rule
:mod:`deepinteract_tpu.analysis.rules.no_print` (the ``hlo_probe.py``
precedent: the implementation moved into the package so one
``python -m deepinteract_tpu.cli.lint`` run covers the whole repo; this
entry point keeps the historical CLI and exit-code contract). Library,
training, serving, and pipeline code must report through ``logging`` or
the telemetry registry (``deepinteract_tpu/obs``) — a stray print
bypasses both and disappears in multi-host runs.

Run directly or via the fast-tier test ``tests/test_no_print.py``::

    python tools/check_no_print.py            # exit 1 + report on violation
    python tools/check_no_print.py --root path/to/package
"""

from __future__ import annotations

import argparse
import ast
import os
import pathlib
import sys
from typing import Iterator

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from deepinteract_tpu.analysis.rules.no_print import (  # noqa: E402
    violations_in_tree,
)

# Package subdirectories where bare print() is the intended UX (the
# historical shim semantics: scan a package root, exempt cli/).
ALLOWED_FIRST_PARTS = {"cli"}


def iter_violations(package_root: pathlib.Path) -> Iterator[str]:
    for path in sorted(package_root.rglob("*.py")):
        rel = path.relative_to(package_root)
        if rel.parts and rel.parts[0] in ALLOWED_FIRST_PARTS:
            continue
        try:
            tree = ast.parse(path.read_text(encoding="utf-8"),
                             filename=str(path))
        except SyntaxError as exc:
            yield f"{path}:{exc.lineno or 0}: unparseable ({exc.msg})"
            continue
        for line, message in violations_in_tree(tree):
            yield f"{path}:{line}: {message}"


def main(argv=None) -> int:
    parser = argparse.ArgumentParser(description=__doc__)
    default_root = pathlib.Path(__file__).resolve().parents[1] / "deepinteract_tpu"
    parser.add_argument("--root", type=pathlib.Path, default=default_root,
                        help="package directory to scan")
    args = parser.parse_args(argv)
    if not args.root.is_dir():
        print(f"error: {args.root} is not a directory", file=sys.stderr)
        return 2
    violations = list(iter_violations(args.root))
    for v in violations:
        print(v)
    if violations:
        print(f"{len(violations)} bare print() call(s) found")
        return 1
    return 0


if __name__ == "__main__":
    sys.exit(main())
