"""Static check: no bare ``print(`` in ``deepinteract_tpu/`` outside ``cli/``.

Library, training, serving, and pipeline code must report through
``logging`` or the telemetry registry (``deepinteract_tpu/obs``) so output
is structured, filterable, and visible to exposition — a stray print
bypasses all three and disappears in multi-host runs. The CLI entry
points (``deepinteract_tpu/cli/``) and the top-level ``bench.py`` are the
sanctioned stdout surfaces and are exempt.

AST-based (not grep): only real ``print(...)`` *calls* to the builtin
name count — ``log_fn=print`` defaults, methods named print, and strings
mentioning print() do not. Run directly or via the fast-tier test
``tests/test_no_print.py``::

    python tools/check_no_print.py            # exit 1 + report on violation
    python tools/check_no_print.py --root path/to/package
"""

from __future__ import annotations

import argparse
import ast
import pathlib
import sys
from typing import Iterator

# Package subdirectories where bare print() is the intended UX.
ALLOWED_FIRST_PARTS = {"cli"}


def iter_violations(package_root: pathlib.Path) -> Iterator[str]:
    for path in sorted(package_root.rglob("*.py")):
        rel = path.relative_to(package_root)
        if rel.parts and rel.parts[0] in ALLOWED_FIRST_PARTS:
            continue
        try:
            tree = ast.parse(path.read_text(encoding="utf-8"),
                             filename=str(path))
        except SyntaxError as exc:
            yield f"{path}:{exc.lineno or 0}: unparseable ({exc.msg})"
            continue
        for node in ast.walk(tree):
            if (isinstance(node, ast.Call)
                    and isinstance(node.func, ast.Name)
                    and node.func.id == "print"):
                yield (f"{path}:{node.lineno}: bare print() — use logging "
                       "or the obs registry (cli/ and bench.py are exempt)")


def main(argv=None) -> int:
    parser = argparse.ArgumentParser(description=__doc__)
    default_root = pathlib.Path(__file__).resolve().parents[1] / "deepinteract_tpu"
    parser.add_argument("--root", type=pathlib.Path, default=default_root,
                        help="package directory to scan")
    args = parser.parse_args(argv)
    if not args.root.is_dir():
        print(f"error: {args.root} is not a directory", file=sys.stderr)
        return 2
    violations = list(iter_violations(args.root))
    for v in violations:
        print(v)
    if violations:
        print(f"{len(violations)} bare print() call(s) found")
        return 1
    return 0


if __name__ == "__main__":
    sys.exit(main())
