"""Real-structure evidence run: 4heq through the full framework CLIs.

Until round 2, every metric this framework ever produced came from
synthetic random walks; the reference's entire reason to exist is model
quality on real complexes (deepinteract_modules.py:2044-2081). The
published DIPS/DB5 corpora and Zenodo checkpoint are unreachable from this
offline image, so this tool extracts the maximum real-structure evidence
from the one real complex the reference ships
(``project/test_data/4heq_{l,r}_u.pdb``, used by its prediction docs):

Stage A — **fit proof** on the full 4heq complex (145x145 residues, 80
interface contacts at the 6 A criterion): featurize with the real
pipeline, overfit the flagship default model (2 GT layers / 128 hidden /
14-chunk dilated decoder) via ``cli.train``, evaluate via ``cli.test``.
Reported AUROC / top-k precision measure the framework's ability to fit
real protein geometry end-to-end — NOT generalization (stated plainly in
BASELINE.md).

Stage B — **pipeline proof**: derive interface-centered residue-window
fragment pairs from 4heq, write them as real PDB files, build a
multi-complex dataset with ``cli.build_dataset`` (real split files), and
run ``cli.train`` -> ``cli.test`` -> per-target CSV end-to-end on data the
builder produced from disk.

Stage C — **held-out generalization protocol** (VERDICT r4 item 4): a
larger fragment-complex corpus (cartesian window pairs over both chains),
partitioned at the COMPLEX level so every test complex appears in no
training or validation batch; train on the train split with early
stopping on val, report the reference top-k metric table on the held-out
complexes. Honesty caveat, stated wherever the numbers are: held-out
complexes are unseen (row, col) window pairs of the same underlying 4heq
structure — unseen complexes, not an unseen protein family; that is the
strongest generalization evidence constructible offline from the one real
complex the reference ships.

Usage (defaults reproduce the BASELINE.md numbers)::

    python tools/real_data_proof.py --work_dir /tmp/realproof \
        [--epochs_a 25] [--epochs_b 12] [--epochs_c 30] [--tiny]
"""

from __future__ import annotations

import argparse
import json
import os
import shutil
import sys
import time

import numpy as np

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

REF_TEST_DATA = "/root/reference/project/test_data"


def tiny_flags():
    return ["--num_gnn_layers", "1", "--num_gnn_hidden_channels", "8",
            "--num_gnn_attention_heads", "2", "--num_interact_layers", "1",
            "--num_interact_hidden_channels", "8"]


def derive_fragment_pairs(work_dir: str, window: int = 100):
    """Write real-geometry fragment pairs (and the full pair) as PDB files.

    Windows are chosen to overlap the 4heq interface so every fragment
    complex keeps positive labels."""
    from deepinteract_tpu.pipeline.pair import interface_labels, load_structure
    from deepinteract_tpu.pipeline.pdb import write_pdb

    left = load_structure(os.path.join(REF_TEST_DATA, "4heq_l_u.pdb"))
    right = load_structure(os.path.join(REF_TEST_DATA, "4heq_r_u.pdb"))
    labels = interface_labels(left, right)

    input_dir = os.path.join(work_dir, "input_pdbs")
    os.makedirs(input_dir, exist_ok=True)
    write_pdb(left, os.path.join(input_dir, "4heq_full_l_u.pdb"))
    write_pdb(right, os.path.join(input_dir, "4heq_full_r_u.pdb"))

    n1, n2 = len(left), len(right)
    window = min(window, n1, n2)  # chains shorter than the window: one full-chain "fragment"
    stride = 15
    starts1 = sorted(set(range(0, n1 - window + 1, stride)) | {n1 - window})
    starts2 = sorted(set(range(0, n2 - window + 1, stride)) | {n2 - window})
    kept = []
    for j, (s1, s2) in enumerate(zip(starts1, starts2)):
        sub = labels[s1 : s1 + window, s2 : s2 + window]
        if sub.sum() == 0:
            continue  # fragment pair without an interface — no labels to fit
        name = f"4heq_frag{j}"
        write_pdb(left.slice_residues(s1, s1 + window),
                  os.path.join(input_dir, f"{name}_l_u.pdb"))
        write_pdb(right.slice_residues(s2, s2 + window),
                  os.path.join(input_dir, f"{name}_r_u.pdb"))
        kept.append((name, int(sub.sum())))
    print(f"fragments kept: {kept} (full pair: {int(labels.sum())} contacts)")
    return input_dir


def derive_cartesian_fragments(work_dir: str, window: int = 100,
                               stride: int = 15, min_contacts: int = 5):
    """Stage C corpus: ALL (row-window, col-window) pairs with at least
    ``min_contacts`` interface contacts, written as real PDB pairs.

    Unlike :func:`derive_fragment_pairs` (diagonal zip, few complexes),
    the cartesian product yields enough distinct complexes to hold some
    out entirely."""
    from deepinteract_tpu.pipeline.pair import interface_labels, load_structure
    from deepinteract_tpu.pipeline.pdb import write_pdb

    left = load_structure(os.path.join(REF_TEST_DATA, "4heq_l_u.pdb"))
    right = load_structure(os.path.join(REF_TEST_DATA, "4heq_r_u.pdb"))
    labels = interface_labels(left, right)

    input_dir = os.path.join(work_dir, "input_pdbs_c")
    # Clear any previous derivation: a rerun with a different stride in
    # the same work_dir must not leave stale windows that build_dataset
    # would fold into dataset_c alongside the new set.
    shutil.rmtree(input_dir, ignore_errors=True)
    os.makedirs(input_dir, exist_ok=True)
    n1, n2 = len(left), len(right)
    window = min(window, n1, n2)
    starts1 = sorted(set(range(0, n1 - window + 1, stride)) | {n1 - window})
    starts2 = sorted(set(range(0, n2 - window + 1, stride)) | {n2 - window})
    kept = []
    for s1 in starts1:
        for s2 in starts2:
            sub = labels[s1 : s1 + window, s2 : s2 + window]
            if int(sub.sum()) < min_contacts:
                continue
            name = f"4heq_w{s1:03d}_{s2:03d}"
            write_pdb(left.slice_residues(s1, s1 + window),
                      os.path.join(input_dir, f"{name}_l_u.pdb"))
            write_pdb(right.slice_residues(s2, s2 + window),
                      os.path.join(input_dir, f"{name}_r_u.pdb"))
            kept.append((name, int(sub.sum())))
    print(f"stage C fragments kept: {len(kept)} "
          f"({[k for k, _ in kept]})")
    if len(kept) < 6:
        raise SystemExit(
            "stage C needs >= 6 fragment complexes for a held-out split; "
            "lower --min_contacts or the stride")
    return input_dir, [k for k, _ in kept]


def heldout_split(names):
    """Complex-level partition: every 4th complex (by sorted name) is held
    out for test; of the rest, every 5th is val, remainder train. The
    test complexes appear in no training or validation batch — the
    disjointness STAGE C exists to prove (asserted by the caller)."""
    names = sorted(names)
    test = names[::4]
    rest = [n for n in names if n not in test]
    val = rest[::5]
    train = [n for n in rest if n not in val]
    return train, val, test


def build_dataset(input_dir: str, out_dir: str) -> None:
    from deepinteract_tpu.cli.build_dataset import main as build_main

    rc = build_main(["--input_dir", input_dir, "--output_dir", out_dir])
    if rc != 0:
        raise SystemExit("cli.build_dataset failed")


def overwrite_splits(root: str, train, val, test) -> None:
    from deepinteract_tpu.data.analysis import write_split_files

    write_split_files(root, {"train": train, "val": val, "test": test})


def run_train(root: str, ckpt_dir: str, epochs: int, extra=()):
    from deepinteract_tpu.cli.train import main as train_main

    args = ["--dips_root", root, "--ckpt_dir", ckpt_dir,
            "--num_epochs", str(epochs), "--patience", str(epochs),
            "--viz_every_n_epochs", "0", "--log_every", "50"]
    args += list(extra)
    rc = train_main(args)
    if rc != 0:
        raise SystemExit("cli.train failed")


def run_test(root: str, ckpt_dir: str, csv_out: str, extra=()):
    """cli.test prints 'metric: value' lines; capture them."""
    import contextlib
    import io

    from deepinteract_tpu.cli.test import main as test_main

    buf = io.StringIO()
    args = ["--dips_root", root, "--ckpt_name", ckpt_dir, "--csv_out", csv_out]
    args += list(extra)
    with contextlib.redirect_stdout(buf):
        rc = test_main(args)
    sys.stdout.write(buf.getvalue())
    if rc != 0:
        raise SystemExit("cli.test failed")
    metrics = {}
    for line in buf.getvalue().splitlines():
        if ": " in line and not line.startswith("wrote"):
            k, _, v = line.partition(": ")
            try:
                metrics[k.strip()] = float(v)
            except ValueError:
                pass
    return metrics


def main(argv=None) -> int:
    p = argparse.ArgumentParser(description=__doc__)
    p.add_argument("--work_dir", default="/tmp/real_data_proof")
    p.add_argument("--epochs_a", type=int, default=25)
    p.add_argument("--epochs_b", type=int, default=12)
    p.add_argument("--train_repeat", type=int, default=8,
                   help="stage A: list the complex this many times per "
                        "epoch (8 steps/epoch -> one scanned dispatch)")
    p.add_argument("--tiny", action="store_true",
                   help="tiny model (CI-scale smoke, not the proof run)")
    p.add_argument("--epochs_c", type=int, default=30)
    p.add_argument("--stride_c", type=int, default=15,
                   help="stage C window stride; smaller = more fragment "
                        "complexes (denser corpus, more held-out targets)")
    p.add_argument("--skip_a", action="store_true")
    p.add_argument("--skip_b", action="store_true")
    p.add_argument("--skip_c", action="store_true")
    args = p.parse_args(argv)

    if not os.path.isdir(REF_TEST_DATA):
        raise SystemExit(f"{REF_TEST_DATA} not found (reference not mounted)")
    os.makedirs(args.work_dir, exist_ok=True)
    model_flags = tiny_flags() if args.tiny else []
    results = {}

    # Stage C derives its own (cartesian) corpus; the diagonal fragment
    # set only feeds stages A and B.
    input_dir = (derive_fragment_pairs(args.work_dir)
                 if not (args.skip_a and args.skip_b) else None)

    if not args.skip_a:
        t0 = time.time()
        root_a = os.path.join(args.work_dir, "dataset_a")
        build_dataset(input_dir, root_a)
        # Fit proof: train/val/test are all the full 4heq complex.
        full = "4heq_full.npz"
        overwrite_splits(root_a, [full] * args.train_repeat, [full], [full])
        ckpt_a = os.path.join(args.work_dir, "ckpt_a")
        shutil.rmtree(ckpt_a, ignore_errors=True)
        run_train(root_a, ckpt_a, args.epochs_a, model_flags)
        csv_a = os.path.join(args.work_dir, "stage_a_top_metrics.csv")
        m = run_test(root_a, ckpt_a, csv_a, model_flags)
        m["wall_seconds"] = time.time() - t0
        results["stage_a_4heq_fit"] = m
        print(f"stage A done in {m['wall_seconds']:.0f}s")

    if not args.skip_b:
        t0 = time.time()
        root_b = os.path.join(args.work_dir, "dataset_b")
        build_dataset(input_dir, root_b)  # real 80/20/25 split files kept
        for mode in ("train", "val", "test"):
            with open(os.path.join(root_b, f"pairs-postprocessed-{mode}.txt")) as fh:
                assert fh.read().strip(), (
                    f"{mode} split is empty — too few fragment complexes "
                    f"for the 80/20/25 partition; lower the stride"
                )
        ckpt_b = os.path.join(args.work_dir, "ckpt_b")
        shutil.rmtree(ckpt_b, ignore_errors=True)
        run_train(root_b, ckpt_b, args.epochs_b, model_flags)
        csv_b = os.path.join(args.work_dir, "stage_b_top_metrics.csv")
        m = run_test(root_b, ckpt_b, csv_b, model_flags)
        m["wall_seconds"] = time.time() - t0
        results["stage_b_builder_end_to_end"] = m
        assert os.path.exists(csv_b)
        print(f"stage B done in {m['wall_seconds']:.0f}s; CSV at {csv_b}")

    if not args.skip_c:
        t0 = time.time()
        input_dir_c, names = derive_cartesian_fragments(
            args.work_dir, stride=args.stride_c)
        root_c = os.path.join(args.work_dir, "dataset_c")
        build_dataset(input_dir_c, root_c)
        train, val, test = heldout_split(names)
        print(f"stage C split: {len(train)} train / {len(val)} val / "
              f"{len(test)} HELD-OUT test: {test}")
        assert not (set(test) & set(train)) and not (set(test) & set(val))
        overwrite_splits(root_c, [f"{n}.npz" for n in train],
                         [f"{n}.npz" for n in val],
                         [f"{n}.npz" for n in test])
        ckpt_c = os.path.join(args.work_dir, "ckpt_c")
        shutil.rmtree(ckpt_c, ignore_errors=True)
        run_train(root_c, ckpt_c, args.epochs_c, model_flags)
        csv_c = os.path.join(args.work_dir, "stage_c_top_metrics.csv")
        m = run_test(root_c, ckpt_c, csv_c, model_flags)
        m["wall_seconds"] = time.time() - t0
        m["n_train"], m["n_val"], m["n_heldout"] = (
            len(train), len(val), len(test))
        results["stage_c_heldout_generalization"] = m
        print(f"stage C done in {m['wall_seconds']:.0f}s; held-out "
              f"metrics above; CSV at {csv_c}")

    print(json.dumps(results, indent=2, sort_keys=True))
    with open(os.path.join(args.work_dir, "results.json"), "w") as fh:
        json.dump(results, fh, indent=2, sort_keys=True)
    return 0


if __name__ == "__main__":
    sys.exit(main())
