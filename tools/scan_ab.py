"""Scanned-train Pallas-vs-jnp A/B — decision-grade attention routing data.

Single-dispatch A/B timings through the axon tunnel carry ±10-20% spread
(BASELINE.md), so the r4/r5 per-step numbers (0.95-1.12x) cannot decide
where `attention_impl='auto'` should route TRAIN steps. The K-step
scanned dispatch is the stable protocol: this tool times the same
``multi_train_step`` executable with each forced implementation and
prints per-step times + the ratio.

Usage: python tools/scan_ab.py [batch] [pad] [dtype]   (defaults 8 128
float32; dtype also accepts bfloat16 for the END-TO-END policy —
encoder + attention + decoder, models/policy.py)

When DI_ATTENTION_AB points at an evidence file, the measured scanned
speedup is RECORDED there (attention_ab/v1), and `attention_impl='auto'`
routing consults it: a bucket where the kernel loses (<= 1.0x)
demonstrably falls back to jnp with the reason logged
(ops/pallas_attention.py:resolve_attention_impl) — the autotune guard
ISSUE-10 added so a measured loss can never ship as the default again.
"""

from __future__ import annotations

import dataclasses
import json
import os
import sys
import time

import numpy as np

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


def main() -> int:
    import jax

    from deepinteract_tpu.data.graph import stack_complexes
    from deepinteract_tpu.data.synthetic import random_complex
    from deepinteract_tpu.models.model import DeepInteract, ModelConfig
    from deepinteract_tpu.training.optim import OptimConfig
    from deepinteract_tpu.training.steps import (
        create_train_state,
        multi_train_step,
        stack_microbatches,
    )

    bs = int(sys.argv[1]) if len(sys.argv) > 1 else 8
    pad = int(sys.argv[2]) if len(sys.argv) > 2 else 128
    dtype = sys.argv[3] if len(sys.argv) > 3 else "float32"
    scan_k = 8
    n1, n2 = {128: (100, 80), 256: (230, 200)}[pad]
    rng = np.random.default_rng(0)
    batch = stack_complexes([
        random_complex(n1, n2, rng=rng, n_pad1=pad, n_pad2=pad, knn=20,
                       geo_nbrhd_size=2)
        for _ in range(bs)
    ])
    print(f"device={jax.devices()[0].device_kind} b{bs} p{pad} scan{scan_k}",
          flush=True)

    results = {}
    state_cache = {}
    for impl in ("jnp", "pallas"):
        base = ModelConfig()
        model = DeepInteract(dataclasses.replace(
            base,
            gnn=dataclasses.replace(base.gnn, attention_impl=impl),
            decoder=dataclasses.replace(base.decoder, remat=True),
            # End-to-end policy dtype (encoder + attention + decoder):
            # the gen-2 kernel runs its MXU gathers in this dtype, so the
            # A/B must measure the dtype it will route for.
            compute_dtype=dtype,
        ))
        if "state" not in state_cache:
            state_cache["state"] = create_train_state(
                model, jax.tree_util.tree_map(lambda x: x[:1], batch),
                optim_cfg=OptimConfig(steps_per_epoch=100, num_epochs=50))
        # Same param tree for both impls — swap only the apply_fn.
        state = state_cache["state"].replace(apply_fn=model.apply)
        stacked = stack_microbatches([batch] * scan_k)
        mstep = jax.jit(lambda s, bst: multi_train_step(s, bst))
        t0 = time.perf_counter()
        compiled = mstep.lower(state, stacked).compile()
        compile_s = time.perf_counter() - t0

        def run(ncalls):
            out = None
            t0 = time.perf_counter()
            for _ in range(ncalls):
                out = compiled(state, stacked)
            jax.block_until_ready(out)
            # Forced host fetch: dispatch-only timing lies via the tunnel.
            float(np.asarray(jax.device_get(out[1]["loss"])).ravel()[0])
            return time.perf_counter() - t0

        run(1)  # warmup
        samples = []
        for _ in range(3):
            t1, t2 = run(1), run(2)
            samples.append((t2 - t1) / scan_k)
        per_step = float(np.median(samples))
        results[impl] = {"per_step_ms": per_step * 1e3,
                         "complexes_per_sec": bs / per_step,
                         "compile_s": compile_s}
        print(f"{impl}: {per_step*1e3:.2f} ms/step "
              f"({bs/per_step:.1f} c/s, compile {compile_s:.0f}s)", flush=True)

    results["pallas_speedup_train_scan"] = (
        results["jnp"]["per_step_ms"] / results["pallas"]["per_step_ms"])
    ab_path = os.environ.get("DI_ATTENTION_AB")
    if ab_path:
        from deepinteract_tpu.ops.pallas_attention import record_attention_ab

        record_attention_ab(
            ab_path, bs, pad, dtype,
            train_scan_speedup=results["pallas_speedup_train_scan"])
        results["evidence_recorded"] = ab_path
    print("RESULT " + json.dumps(results))
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
