"""Static check: no hardcoded float dtypes in ``models/`` outside
``models/policy.py``.

Thin shim over the framework rule
:mod:`deepinteract_tpu.analysis.rules.dtype_discipline` (the
``hlo_probe.py`` precedent: the implementation moved into the package so
one ``python -m deepinteract_tpu.cli.lint`` run covers the whole repo;
this entry point keeps the historical CLI and exit-code contract). The
dtype policy (``deepinteract_tpu/models/policy.py``) is the single place
model code may name a precision — stray ``jnp.float32`` casts are the
"f32 islands" that neutralized bf16 in the pre-r6 decoder.

Run directly or via the fast-tier test
``tests/test_dtype_discipline.py``::

    python tools/check_dtype_discipline.py        # exit 1 + report
    python tools/check_dtype_discipline.py --root path/to/models
"""

from __future__ import annotations

import argparse
import ast
import os
import pathlib
import sys
from typing import Iterator

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from deepinteract_tpu.analysis.rules.dtype_discipline import (  # noqa: E402
    ALLOWED_FILES,
    violations_in_tree,
)


def iter_violations(models_root: pathlib.Path) -> Iterator[str]:
    for path in sorted(models_root.rglob("*.py")):
        if path.name in ALLOWED_FILES:
            continue
        try:
            tree = ast.parse(path.read_text(encoding="utf-8"),
                             filename=str(path))
        except SyntaxError as exc:
            yield f"{path}:{exc.lineno or 0}: unparseable ({exc.msg})"
            continue
        for line, message in violations_in_tree(tree):
            yield f"{path}:{line}: {message}"


def main(argv=None) -> int:
    parser = argparse.ArgumentParser(description=__doc__)
    default_root = (pathlib.Path(__file__).resolve().parents[1]
                    / "deepinteract_tpu" / "models")
    parser.add_argument("--root", type=pathlib.Path, default=default_root,
                        help="models directory to scan")
    args = parser.parse_args(argv)
    if not args.root.is_dir():
        print(f"error: {args.root} is not a directory", file=sys.stderr)
        return 2
    violations = list(iter_violations(args.root))
    for v in violations:
        print(v)
    if violations:
        print(f"{len(violations)} hardcoded dtype reference(s) found")
        return 1
    return 0


if __name__ == "__main__":
    sys.exit(main())
