"""Static check: no hardcoded float dtypes in ``models/`` outside
``models/policy.py``.

The dtype policy (``deepinteract_tpu/models/policy.py``) is the single
place model code may name a precision: statistics accumulate in
``STATS_DTYPE``, outward-facing tensors are ``OUTPUT_DTYPE``, activations
follow the configured compute dtype. A stray ``jnp.float32`` cast inside
a model silently pins part of the graph to full precision (the pre-r6
decoder had exactly such islands, which neutralized bf16 until they were
hunted down one by one) — or worse, a stray ``jnp.bfloat16`` bypasses the
policy's float32 guarantees for params/norms/logits.

AST-based (not grep): only real attribute references to the dtype names
on the ``jnp`` / ``np`` / ``jax.numpy`` / ``numpy`` modules count —
strings mentioning 'float32' (config values like
``compute_dtype="float32"``) and comparisons against those strings do
not. Run directly or via the fast-tier test
``tests/test_dtype_discipline.py``::

    python tools/check_dtype_discipline.py        # exit 1 + report
    python tools/check_dtype_discipline.py --root path/to/models
"""

from __future__ import annotations

import argparse
import ast
import pathlib
import sys
from typing import Iterator

# Files inside the scanned root where naming a dtype is the point.
ALLOWED_FILES = {"policy.py"}

# Forbidden attribute names on a numpy-ish module object.
DTYPE_ATTRS = {"float32", "bfloat16", "float16", "float64"}

# Module aliases whose dtype attributes count as hardcoding.
NUMPY_MODULES = {"jnp", "np", "numpy"}


def _is_numpy_module(node: ast.expr) -> bool:
    """True for ``jnp`` / ``np`` / ``numpy`` names and ``jax.numpy``."""
    if isinstance(node, ast.Name):
        return node.id in NUMPY_MODULES
    if isinstance(node, ast.Attribute):  # jax.numpy
        return (isinstance(node.value, ast.Name)
                and node.value.id == "jax" and node.attr == "numpy")
    return False


def iter_violations(models_root: pathlib.Path) -> Iterator[str]:
    for path in sorted(models_root.rglob("*.py")):
        if path.name in ALLOWED_FILES:
            continue
        try:
            tree = ast.parse(path.read_text(encoding="utf-8"),
                             filename=str(path))
        except SyntaxError as exc:
            yield f"{path}:{exc.lineno or 0}: unparseable ({exc.msg})"
            continue
        for node in ast.walk(tree):
            if (isinstance(node, ast.Attribute)
                    and node.attr in DTYPE_ATTRS
                    and _is_numpy_module(node.value)):
                yield (f"{path}:{node.lineno}: hardcoded dtype "
                       f"'{ast.unparse(node)}' — import it from "
                       "models/policy.py (STATS_DTYPE / OUTPUT_DTYPE / "
                       "FLOAT32 / compute_dtype()) so precision has one "
                       "authority")


def main(argv=None) -> int:
    parser = argparse.ArgumentParser(description=__doc__)
    default_root = (pathlib.Path(__file__).resolve().parents[1]
                    / "deepinteract_tpu" / "models")
    parser.add_argument("--root", type=pathlib.Path, default=default_root,
                        help="models directory to scan")
    args = parser.parse_args(argv)
    if not args.root.is_dir():
        print(f"error: {args.root} is not a directory", file=sys.stderr)
        return 2
    violations = list(iter_violations(args.root))
    for v in violations:
        print(v)
    if violations:
        print(f"{len(violations)} hardcoded dtype reference(s) found")
        return 1
    return 0


if __name__ == "__main__":
    sys.exit(main())
