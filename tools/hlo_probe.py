"""Compiled-HLO launch census for the decoder: where do the kernels go?

Compiles the full decoder forward (masked, depad) and the mask=None
variant for the real TPU backend and prints per-opcode top-level op
counts of the optimized HLO entry computation — the number of kernel
launches XLA actually schedules. The masked-vs-unmasked launch delta
localizes the ~3.3 ms gap measured by tools/decoder_ablation.py better
than micro-benchmarks can.

Usage: python tools/hlo_probe.py [pad]
"""

from __future__ import annotations

import os
import re
import sys
from collections import Counter

import numpy as np

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


def census(txt: str) -> Counter:
    """Opcode counts of the ENTRY computation's top-level ops."""
    counts: Counter = Counter()
    in_entry = False
    for line in txt.splitlines():
        if line.startswith("ENTRY "):
            in_entry = True
            continue
        if in_entry:
            if line.startswith("}"):
                break
            m = re.match(r"\s+\S+ = \S+ ([a-z0-9\-]+)[.(]", line)
            if m:
                counts[m.group(1)] += 1
    return counts


def main() -> int:
    import jax
    import jax.numpy as jnp

    from deepinteract_tpu.models.decoder import DecoderConfig, InteractionDecoder

    pad = int(sys.argv[1]) if len(sys.argv) > 1 else 128
    print(f"device={jax.devices()[0].device_kind} pad={pad}", flush=True)
    rng = np.random.default_rng(0)
    x = jnp.asarray(rng.standard_normal((1, pad, pad, 256)).astype(np.float32))
    mask_np = np.zeros((1, pad, pad), bool)
    mask_np[:, : pad - 20, : pad - 28] = True
    mask = jnp.asarray(mask_np)
    model = InteractionDecoder(DecoderConfig())
    variables = model.init(jax.random.PRNGKey(0), x, mask)

    results = {}
    for name, m in (("masked", mask), ("no-mask", None)):
        compiled = jax.jit(
            lambda v, xx, mm=m: model.apply(v, xx, mm)
        ).lower(variables, x).compile()
        txt = compiled.as_text()
        c = census(txt)
        results[name] = c
        total = sum(c.values())
        print(f"\n{name}: {total} top-level entry ops")
        for op, n in c.most_common(12):
            print(f"  {op:24s} {n}")
        # Per-computation census: the scan body is where the 14 chunks live.
        comps = {}
        cur = None
        for line in txt.splitlines():
            m = re.match(r"(?:ENTRY )?%?([\w.\-]+)[ ]*\([^)]*\) -> ", line)
            if m:
                cur = m.group(1)
                comps[cur] = Counter()
                continue
            if cur is not None:
                if line.startswith("}"):
                    cur = None
                    continue
                m2 = re.match(r"\s+\S+ = \S+ ([a-z0-9\-]+)[.(]", line)
                if m2:
                    comps[cur][m2.group(1)] += 1
        big = sorted(comps.items(), key=lambda kv: -sum(kv[1].values()))[:4]
        for cname, cc in big:
            interesting = {k: v for k, v in cc.most_common(8)}
            print(f"  comp {cname[:40]:40s} {sum(cc.values()):4d} ops "
                  f"{interesting}")
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
