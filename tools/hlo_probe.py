"""Compiled-HLO launch census for the decoder: where do the kernels go?

Thin CLI shim over :mod:`deepinteract_tpu.obs.hloquery` (the census
moved there so the attribution layer — ``obs/attribution.py`` /
``cli/attribute.py`` — can join launch *counts* against measured per-op
*time*). Compiles the full decoder forward (masked, depad) and the
mask=None variant for the current backend and prints per-opcode
top-level op counts of the optimized HLO entry computation, plus the
biggest inner computations (the scan body is where the chunks live).

Usage: python tools/hlo_probe.py [pad]
"""

from __future__ import annotations

import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from deepinteract_tpu.obs.hloquery import (  # noqa: E402
    entry_census,
    top_computations,
)

# Back-compat alias: the census used to be defined here.
census = entry_census


def main() -> int:
    import jax
    import jax.numpy as jnp
    import numpy as np

    from deepinteract_tpu.models.decoder import DecoderConfig, InteractionDecoder

    pad = int(sys.argv[1]) if len(sys.argv) > 1 else 128
    print(f"device={jax.devices()[0].device_kind} pad={pad}", flush=True)
    rng = np.random.default_rng(0)
    x = jnp.asarray(rng.standard_normal((1, pad, pad, 256)).astype(np.float32))
    mask_np = np.zeros((1, pad, pad), bool)
    mask_np[:, : pad - 20, : pad - 28] = True
    mask = jnp.asarray(mask_np)
    model = InteractionDecoder(DecoderConfig())
    variables = model.init(jax.random.PRNGKey(0), x, mask)

    for name, m in (("masked", mask), ("no-mask", None)):
        compiled = jax.jit(
            lambda v, xx, mm=m: model.apply(v, xx, mm)
        ).lower(variables, x).compile()
        txt = compiled.as_text()
        c = entry_census(txt)
        total = sum(c.values())
        print(f"\n{name}: {total} top-level entry ops")
        for op, n in c.most_common(12):
            print(f"  {op:24s} {n}")
        for cname, cc in top_computations(txt, n=4):
            interesting = {k: v for k, v in cc.most_common(8)}
            print(f"  comp {cname[:40]:40s} {sum(cc.values()):4d} ops "
                  f"{interesting}")
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
