"""Assert a bench stdout capture ends in a parseable contract line.

The driver parses the LAST line of its capture as the headline JSON
record. Twice (BENCH_r01, BENCH_r05) a run finished with real numbers but
landed ``"parsed": null`` because the last line was something else (the
multi-hundred-KB stderr DETAIL dump, once; a stray log line, once). This
tool makes that failure mode un-regressable: it validates that the final
non-empty line of a capture parses as JSON and carries the contract keys
bench.py promises. Wired as a fast-tier test
(tests/test_bench_contract.py) against bench's own headline builder, and
usable standalone against a real capture::

    python tools/check_bench_contract.py bench_stdout.log
    some-driver | tee log; python tools/check_bench_contract.py log
"""

from __future__ import annotations

import json
import sys

REQUIRED_KEYS = ("metric", "value", "unit", "vs_baseline")


def check_contract_text(text: str):
    """Validate ``text``'s final non-empty line as the contract record.

    Returns the parsed record dict; raises ValueError with a precise
    reason otherwise (no line / not JSON / missing or mistyped keys)."""
    lines = [ln for ln in text.splitlines() if ln.strip()]
    if not lines:
        raise ValueError("capture is empty — no contract line to parse")
    last = lines[-1].strip()
    try:
        record = json.loads(last)
    except json.JSONDecodeError as exc:
        raise ValueError(
            f"final line is not JSON ({exc}); the driver would record "
            f'"parsed": null. Line was: {last[:200]!r}')
    if not isinstance(record, dict):
        raise ValueError(f"final line parses to {type(record).__name__}, "
                         "not an object")
    missing = [k for k in REQUIRED_KEYS if k not in record]
    if missing:
        raise ValueError(f"contract record is missing keys {missing}; "
                         f"got {sorted(record)}")
    for key in ("value", "vs_baseline"):
        if not isinstance(record[key], (int, float)):
            raise ValueError(
                f"contract key {key!r} must be a number, got "
                f"{type(record[key]).__name__} ({record[key]!r})")
    if not isinstance(record["metric"], str) or not record["metric"]:
        raise ValueError("contract key 'metric' must be a non-empty string")
    if "partial" in record and record["partial"] is not True:
        raise ValueError("'partial' marker, when present, must be true "
                         "(absent means the run was complete)")
    return record


def main(argv=None) -> int:
    argv = sys.argv[1:] if argv is None else argv
    if argv and argv[0] not in ("-",):
        with open(argv[0]) as fh:
            text = fh.read()
    else:
        text = sys.stdin.read()
    try:
        record = check_contract_text(text)
    except ValueError as exc:
        print(f"BENCH CONTRACT VIOLATION: {exc}", file=sys.stderr)
        return 1
    print(json.dumps({"contract_ok": True, "metric": record["metric"],
                      "value": record["value"],
                      "partial": bool(record.get("partial", False))}))
    return 0


if __name__ == "__main__":
    sys.exit(main())
