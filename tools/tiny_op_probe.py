"""Launch-cost probe: 112 tiny pad-value transforms as conv vs dot.

The r5 pad-value-tracking decoder applies each 1x1 conv to BOTH the
[B, H, W, C] map and the [B, 1, 1, C] tracked pad value. The map conv is
MXU work; the pad-value transform is ~8k MACs but, expressed as
``lax.conv_general_dilated``, costs a full conv-kernel launch. 112 of
them per forward (2 per block x 56 blocks) could explain a chunk of the
full-vs-no-mask decoder gap (tools/decoder_ablation.py). This probe
times 112 chained tiny transforms under one jit, expressed three ways.

Usage: python tools/tiny_op_probe.py
"""

from __future__ import annotations

import os
import sys
import time

import numpy as np

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

K = 64  # scan length for differenced timing
N_OPS = 112


def timed(fn, *args):
    import jax

    jfn = jax.jit(fn)
    compiled = jfn.lower(*args).compile()
    out = compiled(*args)
    float(np.asarray(jax.device_get(jax.tree_util.tree_leaves(out)[0])).ravel()[0])

    def run(n):
        t0 = time.perf_counter()
        out = None
        for _ in range(n):
            out = compiled(*args)
        jax.block_until_ready(out)
        float(np.asarray(jax.device_get(
            jax.tree_util.tree_leaves(out)[0])).ravel()[0])
        return time.perf_counter() - t0

    samples = []
    for _ in range(3):
        t1, t2 = run(2), run(4)
        samples.append((t2 - t1) / 2)
    return float(np.median(samples))


def main() -> int:
    import jax
    import jax.numpy as jnp

    print(f"device={jax.devices()[0].device_kind} ops={N_OPS}", flush=True)
    rng = np.random.default_rng(0)
    pv = jnp.asarray(rng.standard_normal((1, 1, 1, 64)).astype(np.float32))
    kernel = jnp.asarray(rng.standard_normal((1, 1, 64, 64)).astype(np.float32) * 0.1)
    bias = jnp.asarray(rng.standard_normal((64,)).astype(np.float32))

    def chain_conv(pv, kernel, bias):
        x = pv
        for _ in range(N_OPS):
            x = jax.lax.conv_general_dilated(
                x, kernel, (1, 1), "VALID",
                dimension_numbers=("NHWC", "HWIO", "NHWC")) + bias
            x = jnp.tanh(x) * 1e-3  # keep magnitudes bounded
        return jnp.sum(x)

    def chain_dot(pv, kernel, bias):
        x = pv[:, 0, 0, :]
        k2 = kernel[0, 0]
        for _ in range(N_OPS):
            x = x @ k2 + bias
            x = jnp.tanh(x) * 1e-3
        return jnp.sum(x)

    def chain_elementwise(pv, kernel, bias):
        x = pv[:, 0, 0, :]
        for _ in range(N_OPS):
            x = x * bias + bias
            x = jnp.tanh(x) * 1e-3
        return jnp.sum(x)

    for name, fn in (("conv1x1", chain_conv), ("dot", chain_dot),
                     ("elementwise", chain_elementwise)):
        t = timed(fn, pv, kernel, bias)
        print(f"{name:12s} {t*1e3:8.3f} ms for {N_OPS} ops "
              f"({t/N_OPS*1e6:.1f} us/op)", flush=True)
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
