"""Sustained end-to-end training throughput proof (VERDICT r3 item 3).

Builds a DIPS-scale synthetic corpus on disk (default 1,000 train
complexes spread over the 128- and 256-residue buckets, 60 val, 32 test),
then runs the REAL ``cli.train`` on it for several epochs on the live
backend and reports what the Trainer actually sustains — prefetching,
shape runs, scanned dispatch, eval, checkpointing included — next to the
micro-bench scan figure.

Corpus note: by default chain lengths are drawn from [90, 125] and
[200, 250] (50/50), so complexes land in the 128/256 buckets only —
at most 4 distinct (bucket1, bucket2) executable shapes (a full DIPS
run over all four buckets pays up to 16 train-scan compiles, which is
the documented compile tax, not a measurement artifact). With
``--p128_only`` every length comes from [90, 125]: one bucket, one
shape pair, full batches — the flagship-throughput workload.

The FINAL stdout line is a machine-readable ``sustained/v1`` contract
(tools/check_cli_contract.py): sustained complexes/sec, the micro-bench
scan rate measured under the same model/batch/dtype/scan-k (device-
resident arguments — the zero-input-pipeline ceiling), and their ratio
``ratio_vs_scan`` — the input-pipeline efficiency figure ROADMAP item 4
targets at >=0.70 (the r5 flagship run recorded ~0.51 with placement on
the dispatch critical path).

Usage:
    python tools/sustained_train.py [--n_train 1000] [--epochs 3]
        [--out /tmp/sustained_train.json]
        [--packed_cache_dir DIR] [--diagonal_buckets]
        [--p128_only --batch_size 8 --compute_dtype bfloat16]  # flagship
        [--device_prefetch]   # overlap placement with device compute
"""

from __future__ import annotations

import argparse
import json
import os
import re
import sys
import time

import numpy as np

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


def build_corpus(root: str, n_train: int, n_val: int, n_test: int,
                 seed: int = 11, p128_only: bool = False) -> None:
    from deepinteract_tpu.data.features import featurize_chain
    from deepinteract_tpu.data.io import save_complex_npz
    from deepinteract_tpu.data.synthetic import (
        random_backbone,
        random_residue_feats,
    )

    rng = np.random.default_rng(seed)
    processed = os.path.join(root, "processed", "sy")
    os.makedirs(processed, exist_ok=True)

    def chain(n, origin):
        bb = random_backbone(n, rng, origin=origin)
        return featurize_chain(bb, random_residue_feats(n, rng), knn=20,
                               geo_nbrhd_size=2, rng=rng), bb

    def length():
        lo, hi = ((90, 125) if (p128_only or rng.random() < 0.5)
                  else (200, 250))
        return int(rng.integers(lo, hi + 1))

    names = []
    t0 = time.perf_counter()
    total = n_train + n_val + n_test
    for i in range(total):
        n1, n2 = length(), length()
        raw1, bb1 = chain(n1, np.zeros(3))
        raw2, bb2 = chain(n2, np.array([12.0, 0.0, 0.0]))
        # Interface labels from CA distances (6 A criterion analog).
        d = np.linalg.norm(bb1[:, 1, None, :] - bb2[None, :, 1, :], axis=-1)
        contacts = np.argwhere(d < 12.0).astype(np.int32)
        neg = np.argwhere(d >= 12.0).astype(np.int32)
        rng.shuffle(neg)
        neg = neg[: max(len(contacts) * 5, 50)]
        examples = np.concatenate([
            np.concatenate([contacts, np.ones((len(contacts), 1), np.int32)], 1),
            np.concatenate([neg, np.zeros((len(neg), 1), np.int32)], 1),
        ])
        save_complex_npz(os.path.join(processed, f"c{i}.npz"), raw1, raw2,
                         examples, f"c{i}")
        names.append(f"sy/c{i}.npz")
        if (i + 1) % 100 == 0:
            print(f"  built {i + 1}/{total} "
                  f"({(time.perf_counter() - t0):.0f}s)", flush=True)

    # Corpus profile manifest FIRST: reuse must fail loudly on a flag
    # mismatch (a mixed-length corpus silently reused under --p128_only
    # would publish a flagship number measured on a different workload),
    # and the reuse marker is the LAST file written so an interrupted
    # build can never present a marker without its manifest.
    with open(os.path.join(root, "corpus_meta.json"), "w") as fh:
        json.dump({"p128_only": p128_only, "n_train": n_train,
                   "n_val": n_val, "n_test": n_test, "seed": seed}, fh)
    splits = {
        "val": names[n_train:n_train + n_val],
        "test": names[n_train + n_val:],
        # train last: its presence is the reuse marker.
        "train": names[:n_train],
    }
    for mode, chunk in splits.items():
        with open(os.path.join(root, f"pairs-postprocessed-{mode}.txt"), "w") as fh:
            fh.write("\n".join(chunk) + "\n")


# --model_scale tiny: the CPU-rehearsal model (1 GT layer, 32 hidden,
# 4-chunk decoder) forwarded to cli.train AND mirrored by the
# ratio_vs_scan micro-bench below, so numerator and denominator always
# measure the same model. The flagship default stays the real figure;
# tiny exists because a full-size CPU rehearsal is hours of wall for a
# number the TPU round re-measures anyway.
TINY_MODEL_FLAGS = [
    "--num_gnn_layers", "1", "--num_gnn_hidden_channels", "32",
    "--num_gnn_attention_heads", "2", "--num_interact_layers", "4",
    "--num_interact_hidden_channels", "32",
]


def _scale_model_cfg(base, model_scale: str):
    import dataclasses

    if model_scale != "tiny":
        return base
    return dataclasses.replace(
        base,
        gnn=dataclasses.replace(base.gnn, num_layers=1, hidden=32,
                                num_heads=2),
        decoder=dataclasses.replace(base.decoder, num_chunks=4,
                                    num_channels=32),
    )


def measure_scan_rate(batch_size: int, compute_dtype: str, scan_k: int,
                      pad: int = 128, model_scale: str = "flagship") -> float:
    """The micro-bench denominator of ``ratio_vs_scan``: the scanned
    train step at the flagship bucket with DEVICE-RESIDENT arguments —
    what the chip sustains when the input pipeline costs nothing. Same
    model config/remat/dtype/batch/scan-k as the sustained run, same
    differenced timing protocol as bench (tuning/timing.py)."""
    import dataclasses

    import jax

    from deepinteract_tpu.data.graph import stack_complexes
    from deepinteract_tpu.data.synthetic import random_complex
    from deepinteract_tpu.models.model import DeepInteract, ModelConfig
    from deepinteract_tpu.training.optim import OptimConfig
    from deepinteract_tpu.training.steps import (
        create_train_state,
        multi_train_step,
        stack_microbatches,
    )
    from deepinteract_tpu.tuning.timing import time_compiled

    base = ModelConfig()
    base = dataclasses.replace(
        base,
        decoder=dataclasses.replace(base.decoder, remat=True),
        compute_dtype=compute_dtype,
    )
    model = DeepInteract(_scale_model_cfg(base, model_scale))
    rng = np.random.default_rng(0)
    batch = stack_complexes([
        random_complex(100, 110, rng=rng, n_pad1=pad, n_pad2=pad)
        for _ in range(batch_size)
    ])
    state = create_train_state(
        model, jax.tree_util.tree_map(lambda x: x[:1], batch),
        optim_cfg=OptimConfig(steps_per_epoch=100, num_epochs=50))
    stacked = stack_microbatches([batch] * scan_k)
    mstep = jax.jit(lambda s, bs: multi_train_step(s, bs))
    _, timing, _ = time_compiled(
        mstep, (state, stacked),
        iters=int(os.environ.get("DI_SUSTAINED_SCAN_ITERS", "3")),
        reps=2, warmup=1,
        log=lambda m: print(m, file=sys.stderr, flush=True))
    return batch_size * scan_k / timing["median"]


def build_contract(result: dict) -> dict:
    """The ``sustained/v1`` final-line record (kind registered in
    tools/check_cli_contract.py; keys must stay in sync)."""
    return {
        "schema": "sustained/v1",
        "metric": "sustained_complexes_per_sec",
        "value": round(float(result["sustained_complexes_per_sec"]), 3),
        "unit": "complexes/s",
        "ratio_vs_scan": round(float(result["ratio_vs_scan"]), 4),
        "scan_complexes_per_sec": round(
            float(result["scan_complexes_per_sec"]), 3),
        "epochs": int(result["epochs"]),
        "n_train": int(result["n_train_complexes"]),
        "steady_epoch_s": round(float(result["steady_epoch_s"]), 3),
        "device_prefetch": bool(result["device_prefetch"]),
        "steps_per_dispatch": int(result["steps_per_dispatch"]),
        "corpus": result["corpus"],
    }


def main() -> int:
    ap = argparse.ArgumentParser()
    ap.add_argument("--root", default="/tmp/sustained_corpus")
    ap.add_argument("--n_train", type=int, default=1000)
    ap.add_argument("--n_val", type=int, default=60)
    ap.add_argument("--n_test", type=int, default=32)
    ap.add_argument("--epochs", type=int, default=3)
    ap.add_argument("--out", default="/tmp/sustained_train.json")
    ap.add_argument("--ckpt_dir", default="/tmp/sustained_ckpt")
    ap.add_argument("--diagonal_buckets", action="store_true",
                    help="forward cli.train's --diagonal_buckets (2 "
                         "shape-pair compiles on this corpus instead of 4)")
    ap.add_argument("--batch_size", type=int, default=1,
                    help="forward cli.train's --batch_size (the flagship "
                         "throughput config is 8 with --compute_dtype "
                         "bfloat16 on a 128-bucket corpus)")
    ap.add_argument("--compute_dtype", default="float32",
                    choices=("float32", "bfloat16"))
    ap.add_argument("--p128_only", action="store_true",
                    help="draw all chain lengths from [90, 125] so every "
                         "complex lands in the 128 bucket (one shape "
                         "pair; b8 batches always fill)")
    ap.add_argument("--packed_cache_dir", default=None,
                    help="forward cli.train's --packed_cache_dir (mmap "
                         "batch assembly; pack built on first run)")
    ap.add_argument("--device_prefetch", action="store_true",
                    help="forward cli.train's --device_prefetch: batch "
                         "placement (h2d + scan-stacking) double-buffered "
                         "on the input pipeline's placement thread")
    ap.add_argument("--steps_per_dispatch", type=int, default=None,
                    help="forward cli.train's --steps_per_dispatch "
                         "(default: cli.train's own default, 8); also the "
                         "scan-k of the ratio_vs_scan micro-bench")
    ap.add_argument("--scan_cps", type=float, default=None,
                    help="skip the micro-bench and use this known scan "
                         "complexes/sec as the ratio_vs_scan denominator "
                         "(e.g. the bench headline figure on hardware)")
    ap.add_argument("--model_scale", default="flagship",
                    choices=("flagship", "tiny"),
                    help="tiny = the CPU-rehearsal model (forwarded to "
                         "cli.train AND the ratio_vs_scan micro-bench, so "
                         "the ratio stays apples-to-apples); flagship = "
                         "the real figure")
    args = ap.parse_args()

    marker = os.path.join(args.root, "pairs-postprocessed-train.txt")
    if not os.path.exists(marker):
        print(f"building corpus at {args.root} ...", flush=True)
        build_corpus(args.root, args.n_train, args.n_val, args.n_test,
                     p128_only=args.p128_only)
    else:
        meta_path = os.path.join(args.root, "corpus_meta.json")
        meta = (json.load(open(meta_path))
                if os.path.exists(meta_path) else {"p128_only": False})
        if bool(meta.get("p128_only")) != args.p128_only:
            raise SystemExit(
                f"corpus at {args.root} was built with "
                f"p128_only={meta.get('p128_only')} but this run asks for "
                f"p128_only={args.p128_only}; use a different --root (the "
                "length mix changes what the sustained figure measures)")
        print(f"reusing corpus at {args.root} "
              f"(p128_only={bool(meta.get('p128_only'))})", flush=True)
    # The throughput denominator comes from the corpus actually used (a
    # reused corpus may differ from --n_train).
    with open(marker) as fh:
        n_train = sum(1 for line in fh if line.strip())

    # Timestamp the Trainer's epoch log lines to split compile tax (epoch 1)
    # from steady state (later epochs). ``log`` is an instance attribute
    # (log_fn), so wrap it at construction time.
    from deepinteract_tpu.training import loop as loop_mod

    epoch_marks = []
    orig_init = loop_mod.Trainer.__init__

    def patched_init(self, *a, **kw):
        orig_init(self, *a, **kw)
        inner = self.log

        def log(msg):
            # The per-epoch METRIC line only ("epoch N: train_loss=...")
            # — the telemetry/log_every lines also start with "epoch "
            # and would double-count epoch boundaries.
            if isinstance(msg, str) and re.match(r"epoch \d+: ", msg):
                epoch_marks.append((time.perf_counter(), msg))
            inner(msg)

        self.log = log

    loop_mod.Trainer.__init__ = patched_init

    from deepinteract_tpu.cli import train as train_cli

    cli_args = [
        "--dips_root", args.root,
        "--num_epochs", str(args.epochs),
        "--ckpt_dir", args.ckpt_dir,
        "--log_every", "0",
        "--patience", str(args.epochs + 1),
        # 256-bucket complexes need decoder remat on a 16G chip (the
        # scanned decoder's backward residuals OOM without it).
        "--remat",
    ]
    if args.diagonal_buckets:
        cli_args.append("--diagonal_buckets")
    if args.packed_cache_dir:
        cli_args += ["--packed_cache_dir", args.packed_cache_dir]
    if args.batch_size != 1:
        cli_args += ["--batch_size", str(args.batch_size)]
    if args.compute_dtype != "float32":
        cli_args += ["--compute_dtype", args.compute_dtype]
    if args.device_prefetch:
        cli_args.append("--device_prefetch")
    if args.model_scale == "tiny":
        cli_args += TINY_MODEL_FLAGS
    if args.steps_per_dispatch is not None:
        cli_args += ["--steps_per_dispatch", str(args.steps_per_dispatch)]
    t_start = time.perf_counter()
    rc = train_cli.main(cli_args)
    wall = time.perf_counter() - t_start
    assert rc == 0

    epoch_times = []
    prev = t_start
    for ts, _ in epoch_marks:
        epoch_times.append(ts - prev)
        prev = ts
    steady = epoch_times[1:] or epoch_times
    steady_epoch_s = float(np.median(steady))
    sustained_cps = n_train / steady_epoch_s

    # ratio_vs_scan: the sustained end-to-end rate against the scanned
    # micro-bench with device-resident arguments — how much of the
    # hardware's rate the input pipeline lets through (ROADMAP item 4:
    # >=0.70). Same model/batch/dtype/scan-k; measured here unless the
    # operator injected a known figure (--scan_cps).
    # None = cli.train's default (8); explicit values clamp like the
    # trainer does (max(1, k)), so 0 measures the per-step denominator
    # it actually trained with, not the k=8 micro-bench.
    scan_k = (8 if args.steps_per_dispatch is None
              else max(1, args.steps_per_dispatch))
    if args.scan_cps:
        scan_cps = float(args.scan_cps)
    else:
        print("measuring micro-bench scan rate (ratio_vs_scan "
              "denominator) ...", flush=True)
        scan_cps = measure_scan_rate(args.batch_size, args.compute_dtype,
                                     scan_k, model_scale=args.model_scale)
    result = {
        "n_train_complexes": n_train,
        "epochs": len(epoch_times),
        "total_wall_s": wall,
        "epoch_wall_s": epoch_times,
        "first_epoch_s": epoch_times[0] if epoch_times else None,
        "steady_epoch_s": steady_epoch_s,
        "compile_tax_s": (epoch_times[0] - steady_epoch_s) if epoch_times else None,
        "sustained_complexes_per_sec": sustained_cps,
        "scan_complexes_per_sec": scan_cps,
        "ratio_vs_scan": sustained_cps / scan_cps if scan_cps else 0.0,
        "device_prefetch": bool(args.device_prefetch),
        "steps_per_dispatch": scan_k,
        "corpus": {
            "model_scale": args.model_scale,
            "p128_only": bool(args.p128_only),
            "n_train": n_train,
            "n_val": args.n_val,
            "n_test": args.n_test,
            "batch_size": args.batch_size,
            "compute_dtype": args.compute_dtype,
        },
        "note": "sustained = train complexes / median steady-state epoch "
                "wall (epoch 2+); first epoch carries the compile tax and "
                "val/test eval compiles; ratio_vs_scan divides by the "
                "device-resident scanned micro-bench at p128",
    }
    print(json.dumps(result, indent=2))
    with open(args.out, "w") as fh:
        json.dump(result, fh, indent=2)
    print(f"wrote {args.out}")
    # Machine contract LAST (tools/check_cli_contract.py kind
    # "sustained"): drivers parse the final line of the capture.
    print(json.dumps(build_contract(result)), flush=True)
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
