"""Sustained end-to-end training throughput proof (VERDICT r3 item 3).

Builds a DIPS-scale synthetic corpus on disk (default 1,000 train
complexes spread over the 128- and 256-residue buckets, 60 val, 32 test),
then runs the REAL ``cli.train`` on it for several epochs on the live
backend and reports what the Trainer actually sustains — prefetching,
shape runs, scanned dispatch, eval, checkpointing included — next to the
micro-bench scan figure.

Corpus note: by default chain lengths are drawn from [90, 125] and
[200, 250] (50/50), so complexes land in the 128/256 buckets only —
at most 4 distinct (bucket1, bucket2) executable shapes (a full DIPS
run over all four buckets pays up to 16 train-scan compiles, which is
the documented compile tax, not a measurement artifact). With
``--p128_only`` every length comes from [90, 125]: one bucket, one
shape pair, full batches — the flagship-throughput workload.

Usage:
    python tools/sustained_train.py [--n_train 1000] [--epochs 3]
        [--out /tmp/sustained_train.json]
        [--packed_cache_dir DIR] [--diagonal_buckets]
        [--p128_only --batch_size 8 --compute_dtype bfloat16]  # flagship
"""

from __future__ import annotations

import argparse
import json
import os
import sys
import time

import numpy as np

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


def build_corpus(root: str, n_train: int, n_val: int, n_test: int,
                 seed: int = 11, p128_only: bool = False) -> None:
    from deepinteract_tpu.data.features import featurize_chain
    from deepinteract_tpu.data.io import save_complex_npz
    from deepinteract_tpu.data.synthetic import (
        random_backbone,
        random_residue_feats,
    )

    rng = np.random.default_rng(seed)
    processed = os.path.join(root, "processed", "sy")
    os.makedirs(processed, exist_ok=True)

    def chain(n, origin):
        bb = random_backbone(n, rng, origin=origin)
        return featurize_chain(bb, random_residue_feats(n, rng), knn=20,
                               geo_nbrhd_size=2, rng=rng), bb

    def length():
        lo, hi = ((90, 125) if (p128_only or rng.random() < 0.5)
                  else (200, 250))
        return int(rng.integers(lo, hi + 1))

    names = []
    t0 = time.perf_counter()
    total = n_train + n_val + n_test
    for i in range(total):
        n1, n2 = length(), length()
        raw1, bb1 = chain(n1, np.zeros(3))
        raw2, bb2 = chain(n2, np.array([12.0, 0.0, 0.0]))
        # Interface labels from CA distances (6 A criterion analog).
        d = np.linalg.norm(bb1[:, 1, None, :] - bb2[None, :, 1, :], axis=-1)
        contacts = np.argwhere(d < 12.0).astype(np.int32)
        neg = np.argwhere(d >= 12.0).astype(np.int32)
        rng.shuffle(neg)
        neg = neg[: max(len(contacts) * 5, 50)]
        examples = np.concatenate([
            np.concatenate([contacts, np.ones((len(contacts), 1), np.int32)], 1),
            np.concatenate([neg, np.zeros((len(neg), 1), np.int32)], 1),
        ])
        save_complex_npz(os.path.join(processed, f"c{i}.npz"), raw1, raw2,
                         examples, f"c{i}")
        names.append(f"sy/c{i}.npz")
        if (i + 1) % 100 == 0:
            print(f"  built {i + 1}/{total} "
                  f"({(time.perf_counter() - t0):.0f}s)", flush=True)

    # Corpus profile manifest FIRST: reuse must fail loudly on a flag
    # mismatch (a mixed-length corpus silently reused under --p128_only
    # would publish a flagship number measured on a different workload),
    # and the reuse marker is the LAST file written so an interrupted
    # build can never present a marker without its manifest.
    with open(os.path.join(root, "corpus_meta.json"), "w") as fh:
        json.dump({"p128_only": p128_only, "n_train": n_train,
                   "n_val": n_val, "n_test": n_test, "seed": seed}, fh)
    splits = {
        "val": names[n_train:n_train + n_val],
        "test": names[n_train + n_val:],
        # train last: its presence is the reuse marker.
        "train": names[:n_train],
    }
    for mode, chunk in splits.items():
        with open(os.path.join(root, f"pairs-postprocessed-{mode}.txt"), "w") as fh:
            fh.write("\n".join(chunk) + "\n")


def main() -> int:
    ap = argparse.ArgumentParser()
    ap.add_argument("--root", default="/tmp/sustained_corpus")
    ap.add_argument("--n_train", type=int, default=1000)
    ap.add_argument("--n_val", type=int, default=60)
    ap.add_argument("--n_test", type=int, default=32)
    ap.add_argument("--epochs", type=int, default=3)
    ap.add_argument("--out", default="/tmp/sustained_train.json")
    ap.add_argument("--ckpt_dir", default="/tmp/sustained_ckpt")
    ap.add_argument("--diagonal_buckets", action="store_true",
                    help="forward cli.train's --diagonal_buckets (2 "
                         "shape-pair compiles on this corpus instead of 4)")
    ap.add_argument("--batch_size", type=int, default=1,
                    help="forward cli.train's --batch_size (the flagship "
                         "throughput config is 8 with --compute_dtype "
                         "bfloat16 on a 128-bucket corpus)")
    ap.add_argument("--compute_dtype", default="float32",
                    choices=("float32", "bfloat16"))
    ap.add_argument("--p128_only", action="store_true",
                    help="draw all chain lengths from [90, 125] so every "
                         "complex lands in the 128 bucket (one shape "
                         "pair; b8 batches always fill)")
    ap.add_argument("--packed_cache_dir", default=None,
                    help="forward cli.train's --packed_cache_dir (mmap "
                         "batch assembly; pack built on first run)")
    args = ap.parse_args()

    marker = os.path.join(args.root, "pairs-postprocessed-train.txt")
    if not os.path.exists(marker):
        print(f"building corpus at {args.root} ...", flush=True)
        build_corpus(args.root, args.n_train, args.n_val, args.n_test,
                     p128_only=args.p128_only)
    else:
        meta_path = os.path.join(args.root, "corpus_meta.json")
        meta = (json.load(open(meta_path))
                if os.path.exists(meta_path) else {"p128_only": False})
        if bool(meta.get("p128_only")) != args.p128_only:
            raise SystemExit(
                f"corpus at {args.root} was built with "
                f"p128_only={meta.get('p128_only')} but this run asks for "
                f"p128_only={args.p128_only}; use a different --root (the "
                "length mix changes what the sustained figure measures)")
        print(f"reusing corpus at {args.root} "
              f"(p128_only={bool(meta.get('p128_only'))})", flush=True)
    # The throughput denominator comes from the corpus actually used (a
    # reused corpus may differ from --n_train).
    with open(marker) as fh:
        n_train = sum(1 for line in fh if line.strip())

    # Timestamp the Trainer's epoch log lines to split compile tax (epoch 1)
    # from steady state (later epochs). ``log`` is an instance attribute
    # (log_fn), so wrap it at construction time.
    from deepinteract_tpu.training import loop as loop_mod

    epoch_marks = []
    orig_init = loop_mod.Trainer.__init__

    def patched_init(self, *a, **kw):
        orig_init(self, *a, **kw)
        inner = self.log

        def log(msg):
            if isinstance(msg, str) and msg.startswith("epoch "):
                epoch_marks.append((time.perf_counter(), msg))
            inner(msg)

        self.log = log

    loop_mod.Trainer.__init__ = patched_init

    from deepinteract_tpu.cli import train as train_cli

    cli_args = [
        "--dips_root", args.root,
        "--num_epochs", str(args.epochs),
        "--ckpt_dir", args.ckpt_dir,
        "--log_every", "0",
        "--patience", str(args.epochs + 1),
        # 256-bucket complexes need decoder remat on a 16G chip (the
        # scanned decoder's backward residuals OOM without it).
        "--remat",
    ]
    if args.diagonal_buckets:
        cli_args.append("--diagonal_buckets")
    if args.packed_cache_dir:
        cli_args += ["--packed_cache_dir", args.packed_cache_dir]
    if args.batch_size != 1:
        cli_args += ["--batch_size", str(args.batch_size)]
    if args.compute_dtype != "float32":
        cli_args += ["--compute_dtype", args.compute_dtype]
    t_start = time.perf_counter()
    rc = train_cli.main(cli_args)
    wall = time.perf_counter() - t_start
    assert rc == 0

    epoch_times = []
    prev = t_start
    for ts, _ in epoch_marks:
        epoch_times.append(ts - prev)
        prev = ts
    steady = epoch_times[1:] or epoch_times
    steady_epoch_s = float(np.median(steady))
    result = {
        "n_train_complexes": n_train,
        "epochs": len(epoch_times),
        "total_wall_s": wall,
        "epoch_wall_s": epoch_times,
        "first_epoch_s": epoch_times[0] if epoch_times else None,
        "steady_epoch_s": steady_epoch_s,
        "compile_tax_s": (epoch_times[0] - steady_epoch_s) if epoch_times else None,
        "sustained_complexes_per_sec": n_train / steady_epoch_s,
        "note": "sustained = train complexes / median steady-state epoch "
                "wall (epoch 2+); first epoch carries the compile tax and "
                "val/test eval compiles",
    }
    print(json.dumps(result, indent=2))
    with open(args.out, "w") as fh:
        json.dump(result, fh, indent=2)
    print(f"wrote {args.out}")
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
