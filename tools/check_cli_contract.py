"""Assert a CLI capture ends in the entry point's machine-readable JSON.

Generalizes ``tools/check_bench_contract.py`` (which stays as the bench
headline's dedicated validator) to EVERY CLI whose final stdout line is a
machine contract: drivers and operators parse the LAST line of a capture,
and twice (BENCH_r01, BENCH_r05) a finished run landed ``"parsed": null``
because something else printed last. One validator per contract kind
makes that failure mode un-regressable across the whole CLI surface::

    python -m deepinteract_tpu.cli.screen ... | tee log
    python tools/check_cli_contract.py screen log

    python tools/check_cli_contract.py bench bench_stdout.log
    python tools/check_cli_contract.py tune tune_stdout.log

Wired as a fast-tier test (tests/test_cli_contract.py) against the real
entry points, so a key rename in any of them fails there first.
"""

from __future__ import annotations

import json
import sys

# Contract kinds: required keys + which of them must be numbers. "bench"
# mirrors check_bench_contract.REQUIRED_KEYS (kept in sync by a test).
CONTRACTS = {
    "bench": {
        "required": ("metric", "value", "unit", "vs_baseline"),
        "numeric": ("value", "vs_baseline"),
    },
    "screen": {
        "required": ("metric", "value", "unit", "pairs_total",
                     "pairs_scored", "encode_reuse_ratio",
                     "emb_cache_hit_rate", "ranked_out", "manifest"),
        "numeric": ("value", "pairs_total", "pairs_scored",
                    "encode_reuse_ratio", "emb_cache_hit_rate"),
    },
    "tune": {
        "required": ("tuning_store", "device_kind", "model_signature",
                     "buckets"),
        "numeric": (),
    },
    "predict_topk": {
        "required": ("metric", "value", "unit", "top_k",
                     "top_contacts_out"),
        "numeric": ("value", "top_k"),
    },
    "attribution": {
        "required": ("metric", "value", "unit", "profile_dir",
                     "report_out", "op_launches", "top_ops", "phases",
                     "census_reconciled"),
        "numeric": ("value", "op_launches"),
    },
    "perf_regression": {
        "required": ("metric", "value", "unit", "ok", "baseline",
                     "compared", "regressions"),
        "numeric": ("value", "compared"),
    },
    # lint/v1: python -m deepinteract_tpu.cli.lint (the unified static-
    # analysis run; deepinteract_tpu/analysis).
    "lint": {
        "required": ("schema", "metric", "value", "unit", "ok", "rules",
                     "files_scanned", "findings_total", "findings_new",
                     "findings_baselined", "suppressed", "baseline"),
        "numeric": ("value", "files_scanned", "findings_total",
                    "findings_new", "findings_baselined", "suppressed"),
    },
    # fleet/v1: the fleet router's final stdout line (cli/serve.py
    # --workers N) and every POST /admin/rollover response
    # (serving/router.py FleetRouter.final_contract).
    "fleet": {
        # preemptions + versions are the ISSUE-16 additions: expected
        # capacity losses absorbed (no circuit penalty) and the count of
        # live checkpoint versions behind the router. mesh_shape
        # (ISSUE-20, non-numeric "DxP") is the topology this router
        # requires of its workers — "1x1" for a single-device fleet.
        "required": ("schema", "metric", "value", "unit", "ok",
                     "workers", "healthy", "restarts", "circuit_open",
                     "rollovers", "failovers", "routed", "preemptions",
                     "versions", "mesh_shape"),
        "numeric": ("value", "workers", "healthy", "restarts",
                    "circuit_open", "rollovers", "failovers", "routed",
                    "preemptions", "versions"),
    },
    # versions/v1: GET /admin/versions on the fleet router (serving/
    # router.py versions_record; also cli/serve.py --versions): canary
    # weights, per-version worker counts, shadow evidence, promotions.
    "versions": {
        "required": ("schema", "metric", "value", "unit", "ok",
                     "weights", "workers_by_version", "shadow",
                     "shadow_samples", "promotions"),
        "numeric": ("value", "shadow_samples", "promotions"),
    },
    # fsck/v1: python -m deepinteract_tpu.cli.fsck (durable-artifact
    # verify/quarantine/report; robustness/artifacts.py).
    # stale_heartbeat_hosts + resume_cursor are the ISSUE-14 additions:
    # which hosts went quiet, and where --resume would land.
    # fleet_versions + stale_version_ledgers are the ISSUE-16 additions:
    # per-version worker counts from fleet_state.json, and agreement
    # ledgers no weighted/shadowed version can consume.
    # index_partitions + stale_index_partitions are the ISSUE-17
    # additions: proteome-index partition census, and manifests frozen
    # at a weights_signature no healthy fleet worker serves.
    # calibrations + stale_calibrations + assembly_bundles are the
    # ISSUE-19 additions: fitted calibration census, calibrations frozen
    # at an unserved weights_signature, and verified assembly bundles.
    "fsck": {
        "required": ("schema", "metric", "value", "unit", "ok", "root",
                     "scanned", "verified", "unverified", "corrupt",
                     "quarantined", "tmp_files", "corrupt_paths",
                     "stale_heartbeats", "stale_heartbeat_hosts",
                     "resume_cursor", "fleet_versions",
                     "stale_version_ledgers", "index_partitions",
                     "stale_index_partitions", "calibrations",
                     "stale_calibrations", "assembly_bundles"),
        "numeric": ("value", "scanned", "verified", "unverified",
                    "corrupt", "quarantined", "tmp_files",
                    "stale_heartbeats", "index_partitions",
                    "calibrations", "assembly_bundles"),
    },
    # sustained/v1: tools/sustained_train.py — end-to-end sustained
    # training rate, the device-resident scanned micro-bench it is
    # divided by, and ratio_vs_scan (the ROADMAP item 4 >=0.70 bar);
    # keys must stay in sync with sustained_train.build_contract.
    "sustained": {
        "required": ("schema", "metric", "value", "unit",
                     "ratio_vs_scan", "scan_complexes_per_sec", "epochs",
                     "n_train", "steady_epoch_s", "device_prefetch",
                     "steps_per_dispatch", "corpus"),
        "numeric": ("value", "ratio_vs_scan", "scan_complexes_per_sec",
                    "epochs", "n_train", "steady_epoch_s",
                    "steps_per_dispatch"),
    },
    # index/v1: python -m deepinteract_tpu.cli.index build|verify|merge
    # (the proteome-index lifecycle; deepinteract_tpu/index).
    "index": {
        "required": ("schema", "metric", "value", "unit", "ok", "action",
                     "index_dir", "partitions", "chains", "buckets",
                     "weights_signature", "library_signature", "resumed",
                     "partitions_resumed", "partitions_rebuilt",
                     "encodes_executed", "corrupt", "corrupt_paths",
                     "preempted", "elapsed_s"),
        "numeric": ("value", "partitions", "chains",
                    "partitions_resumed", "partitions_rebuilt",
                    "encodes_executed", "corrupt", "elapsed_s"),
    },
    # query/v1: python -m deepinteract_tpu.cli.query (single-box ranked-
    # partner funnel over a prebuilt index; index/funnel.py).
    "query": {
        "required": ("schema", "metric", "value", "unit", "ok", "query",
                     "index_dir", "chains", "candidates", "top_m",
                     "survivors", "pairs_decoded", "decode_batches",
                     "prefilter_survivor_frac", "partial", "ranked_out",
                     "elapsed_s", "top_partner"),
        "numeric": ("value", "chains", "candidates", "top_m",
                    "survivors", "pairs_decoded", "decode_batches",
                    "prefilter_survivor_frac", "elapsed_s"),
    },
    # assemble/v1: python -m deepinteract_tpu.cli.assemble (k-chain
    # complex scoring: C(k,2) pairs, encode-once, interface graph,
    # calibrated + control scores; deepinteract_tpu/assembly).
    "assemble": {
        "required": ("schema", "metric", "value", "unit", "ok", "chains",
                     "pairs_total", "pairs_scored", "unique_encodes",
                     "encode_cache_hits", "decode_batches",
                     "interface_edges", "interactability",
                     "control_score", "calibrated", "calibration",
                     "weights_signature", "ranked_out", "bundle_out",
                     "elapsed_s"),
        "numeric": ("value", "chains", "pairs_total", "pairs_scored",
                    "unique_encodes", "encode_cache_hits",
                    "decode_batches", "interface_edges",
                    "interactability", "elapsed_s"),
    },
    # calibrate/v1: python -m deepinteract_tpu.cli.calibrate (held-out
    # temperature/isotonic fit with before/after ECE;
    # deepinteract_tpu/calibration).
    "calibrate": {
        "required": ("schema", "metric", "value", "unit", "ok", "method",
                     "temperature", "pairs", "contacts_fit",
                     "contacts_eval", "ece_raw", "ece_calibrated",
                     "improved", "weights_signature", "calibration_out",
                     "elapsed_s"),
        "numeric": ("value", "temperature", "pairs", "contacts_fit",
                    "contacts_eval", "ece_raw", "ece_calibrated",
                    "elapsed_s"),
    },
    # train_supervise/v1: cli/train.py --supervise (training/
    # supervisor.py TrainingSupervisor.contract): supervised restarts,
    # hang kills, circuit state, and the honest child exit code.
    "train_supervise": {
        "required": ("schema", "metric", "value", "unit", "ok",
                     "restarts", "hang_kills", "crashes", "spawns",
                     "circuit_open", "preempted", "child_exit_code",
                     "state", "state_path", "heartbeat_path"),
        "numeric": ("value", "restarts", "hang_kills", "crashes",
                    "spawns"),
    },
}


def final_json_line(text: str) -> dict:
    """Parse the final non-empty line as a JSON object (the shared
    contract discipline); precise ValueError otherwise."""
    lines = [ln for ln in text.splitlines() if ln.strip()]
    if not lines:
        raise ValueError("capture is empty — no contract line to parse")
    last = lines[-1].strip()
    try:
        record = json.loads(last)
    except json.JSONDecodeError as exc:
        raise ValueError(
            f"final line is not JSON ({exc}); a driver would record "
            f'"parsed": null. Line was: {last[:200]!r}')
    if not isinstance(record, dict):
        raise ValueError(f"final line parses to {type(record).__name__}, "
                         "not an object")
    return record


def check_cli_contract_text(text: str, kind: str) -> dict:
    """Validate ``text``'s final non-empty line against the ``kind``
    contract; returns the parsed record, raises ValueError otherwise."""
    if kind not in CONTRACTS:
        raise ValueError(f"unknown contract kind {kind!r} "
                         f"(want one of {sorted(CONTRACTS)})")
    spec = CONTRACTS[kind]
    record = final_json_line(text)
    missing = [k for k in spec["required"] if k not in record]
    if missing:
        raise ValueError(f"{kind} contract is missing keys {missing}; "
                         f"got {sorted(record)}")
    for key in spec["numeric"]:
        if isinstance(record[key], bool) or not isinstance(
                record[key], (int, float)):
            raise ValueError(
                f"{kind} contract key {key!r} must be a number, got "
                f"{type(record[key]).__name__} ({record[key]!r})")
    return record


def main(argv=None) -> int:
    argv = sys.argv[1:] if argv is None else argv
    if not argv:
        print("usage: check_cli_contract.py <kind> [capture-file|-]",
              file=sys.stderr)
        return 2
    kind = argv[0]
    if len(argv) > 1 and argv[1] != "-":
        with open(argv[1]) as fh:
            text = fh.read()
    else:
        text = sys.stdin.read()
    try:
        record = check_cli_contract_text(text, kind)
    except ValueError as exc:
        print(f"CLI CONTRACT VIOLATION: {exc}", file=sys.stderr)
        return 1
    print(json.dumps({"contract_ok": True, "kind": kind,
                      "keys": sorted(record)}))
    return 0


if __name__ == "__main__":
    sys.exit(main())
