"""Generate the checked-in golden full-model parity fixture.

Runs the reference's own torch pipeline (DGLGeometricTransformer + input
embedding + interaction tensor + ResNet2DInputWithOptAttention, via the
mini-DGL shim in tests/reference_oracle.py) on a real featurized graph
pair with live random weights, then saves to
``tests/golden/full_model_parity.npz``:

* ``sd/<key>``   — the reference state_dict (numpy, torch layout),
* ``cx/<field>`` — the stacked PairedComplex our model consumes,
* ``ref_logits`` — the reference's output contact logits [1, 2, N1, N2],
* ``meta/*``     — the model hyperparameters needed to rebuild our config.

This makes ``tests/test_golden_parity.py`` a torch-free, always-on
full-model parity check (VERDICT r3 item 7); the live-oracle variant in
``tests/test_reference_full_parity.py`` remains the slow tier. Regenerate
only when the featurizer or importer schema changes:

    python tools/make_golden_fixture.py
"""

from __future__ import annotations

import os
import sys

import numpy as np

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, os.path.join(REPO, "tests"))

HIDDEN = 16
HEADS = 2
LIMIT = 32
NUM_CHUNKS = 2
N1, N2 = 26, 22
KNN = 6
GEO = 2


def main() -> int:
    import torch

    from reference_oracle import fake_graph_from_raw, import_reference_modules

    from deepinteract_tpu.data.features import featurize_chain
    from deepinteract_tpu.data.graph import PairedComplex, pad_graph, stack_complexes
    from deepinteract_tpu.data.synthetic import random_backbone, random_residue_feats

    mods = import_reference_modules()
    from project.utils.deepinteract_constants import FEATURE_INDICES

    rng = np.random.default_rng(3)

    def chain_raw(n, origin):
        bb = random_backbone(n, rng, origin=origin)
        return featurize_chain(bb, random_residue_feats(n, rng), knn=KNN,
                               geo_nbrhd_size=GEO, rng=rng)

    raw1 = chain_raw(N1, np.zeros(3))
    raw2 = chain_raw(N2, np.array([10.0, 0.0, 0.0]))

    torch.manual_seed(0)
    embed = torch.nn.Linear(113, HIDDEN, bias=False)
    gnn = mods.DGLGeometricTransformer(
        node_count_limit=LIMIT, num_hidden_channels=HIDDEN,
        num_attention_heads=HEADS, dropout_rate=0.0, num_layers=2,
        feature_indices=FEATURE_INDICES,
    )
    dec = mods.ResNet2DInputWithOptAttention(
        num_chunks=NUM_CHUNKS, init_channels=2 * HIDDEN, num_channels=HIDDEN,
        num_classes=2, module_name="interaction",
    )
    g = torch.Generator().manual_seed(7)
    for m in gnn.modules():
        if isinstance(m, torch.nn.BatchNorm1d):
            with torch.no_grad():
                m.running_mean.normal_(0.0, 0.5, generator=g)
                m.running_var.uniform_(0.5, 2.0, generator=g)
    embed.eval(), gnn.eval(), dec.eval()

    def ref_leg(raw):
        gg = fake_graph_from_raw(raw)
        gg.ndata["f"] = embed(gg.ndata["f"])
        gg = gnn(gg)
        return gg.ndata["f"]

    with torch.no_grad():
        f1, f2 = ref_leg(raw1), ref_leg(raw2)
        t = torch.cat(
            [f1.T[None, :, :, None].expand(1, HIDDEN, N1, N2),
             f2.T[None, :, None, :].expand(1, HIDDEN, N1, N2)], dim=1)
        ref_logits = dec(t).numpy()

    sd = {f"node_in_embedding.{k}": v.numpy() for k, v in embed.state_dict().items()}
    sd.update({f"gnn_module.0.{k}": v.numpy() for k, v in gnn.state_dict().items()})
    sd.update({f"interact_module.{k}": v.numpy() for k, v in dec.state_dict().items()})

    cx = stack_complexes([PairedComplex(
        graph1=pad_graph(raw1, N1), graph2=pad_graph(raw2, N2),
        examples=np.zeros((N1 * N2, 3), np.int32),
        example_mask=np.ones(N1 * N2, bool),
        contact_map=np.zeros((N1, N2), np.int32),
    )])

    payload = {f"sd/{k}": np.asarray(v) for k, v in sd.items()}
    for leg in ("graph1", "graph2"):
        gobj = getattr(cx, leg)
        for field in ("node_feats", "coords", "edge_feats", "nbr_idx",
                      "src_nbr_eids", "dst_nbr_eids", "node_mask", "num_nodes"):
            payload[f"cx/{leg}/{field}"] = np.asarray(getattr(gobj, field))
    for field in ("examples", "example_mask", "contact_map"):
        payload[f"cx/{field}"] = np.asarray(getattr(cx, field))
    payload["ref_logits"] = ref_logits
    payload["meta/hidden"] = np.asarray(HIDDEN)
    payload["meta/heads"] = np.asarray(HEADS)
    payload["meta/limit"] = np.asarray(LIMIT)
    payload["meta/num_chunks"] = np.asarray(NUM_CHUNKS)

    out = os.path.join(REPO, "tests", "golden", "full_model_parity.npz")
    os.makedirs(os.path.dirname(out), exist_ok=True)
    np.savez_compressed(out, **payload)
    print(f"wrote {out} ({os.path.getsize(out) / 1e6:.2f} MB, "
          f"{len(payload)} arrays)")
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
